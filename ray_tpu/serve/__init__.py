"""ray_tpu.serve — model serving on replica actors.

Reference analogues: `python/ray/serve/api.py:414` (``serve.run``),
`api.py:242` (``@serve.deployment``), `serve/deployment.py:261`
(``Deployment.bind``).  Architecture: a named controller actor reconciles
deployments onto named replica actors (`ray_tpu/serve/controller.py`);
handles route with power-of-two-choices (`router.py`); HTTP ingress is a
proxy actor (`http_proxy.py`); queue-depth autoscaling runs in the
controller's control loop.

Composition: a bound deployment passed as an init arg to another bind()
is deployed too and replaced with a DeploymentHandle (the reference's
deployment-graph behavior for the common one-level case).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from ray_tpu.serve.batching import batch, batch_sizes_of
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.controller import CONTROLLER_NAME, NAMESPACE, ServeController
from ray_tpu.serve.http_proxy import PROXY_NAME, HTTPProxy
from ray_tpu.serve.router import DeploymentHandle

__all__ = [
    "deployment", "run", "start", "shutdown", "delete", "status",
    "get_deployment_handle", "get_app_handle", "Deployment", "Application",
    "AutoscalingConfig", "DeploymentHandle", "batch", "batch_sizes_of",
    "get_multiplexed_model_id", "multiplexed",
    "run_config",
]

_state_lock = threading.Lock()
_started = False
_http_port: Optional[int] = None


@dataclass
class AutoscalingConfig:
    """Reference analogue: `serve/config.py` AutoscalingConfig /
    `_private/autoscaling_policy.py:95`."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0
    smoothing_factor: float = 0.6

    def to_dict(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "target_ongoing_requests": self.target_ongoing_requests,
            "upscale_delay_s": self.upscale_delay_s,
            "downscale_delay_s": self.downscale_delay_s,
            "smoothing_factor": self.smoothing_factor,
        }


@dataclass
class Deployment:
    """A deployable unit (reference: `serve/deployment.py`)."""

    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    # End-to-end request deadline (reference: Serve request_timeout_s):
    # stamped as an absolute deadline on every replica call this
    # deployment serves — expiry anywhere in the pipeline sheds the work
    # and raises DeadlineExceededError at the caller (HTTP 504 on the
    # proxy).  None = no deadline.
    request_timeout_s: Optional[float] = None
    user_config: Optional[dict] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Optional[dict] = None
    route_prefix: Optional[str] = None

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, **overrides) -> "Deployment":
        import copy

        d = copy.copy(self)
        for k, v in overrides.items():
            if not hasattr(d, k):
                raise TypeError(f"unknown deployment option {k!r}")
            setattr(d, k, v)
        return d


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 100,
               request_timeout_s: Optional[float] = None,
               user_config: Optional[dict] = None,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               ray_actor_options: Optional[dict] = None):
    """``@serve.deployment`` (reference: `serve/api.py:242`)."""

    def wrap(obj):
        if isinstance(autoscaling_config, dict):
            ac = AutoscalingConfig(**autoscaling_config)
        else:
            ac = autoscaling_config
        return Deployment(
            func_or_class=obj,
            name=name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            request_timeout_s=request_timeout_s,
            user_config=user_config,
            autoscaling_config=ac,
            ray_actor_options=ray_actor_options,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


# ---------------------------------------------------------------------------
# Runtime management


def _controller():
    import ray_tpu

    return ray_tpu.get_actor(CONTROLLER_NAME, namespace=NAMESPACE)


def start(http_host: str = "127.0.0.1", http_port: int = 0,
          with_proxy: bool = True):
    """Ensure the controller (and optionally the HTTP proxy) exist."""
    global _started, _http_port
    import ray_tpu

    with _state_lock:
        if _started:
            return
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        # Attach-or-create: a second driver (e.g. the `serve deploy` CLI
        # run twice) must reuse the live controller, not collide on the
        # actor name.
        try:
            ctrl = ray_tpu.get_actor(CONTROLLER_NAME, namespace=NAMESPACE)
        except ValueError:
            ctrl_cls = ray_tpu.remote(
                num_cpus=0.1, name=CONTROLLER_NAME, namespace=NAMESPACE,
                # long-poll listeners each hold a slot while parked
                max_concurrency=64,
            )(ServeController)
            ctrl = ctrl_cls.remote()
        if with_proxy:
            try:
                proxy = ray_tpu.get_actor(PROXY_NAME, namespace=NAMESPACE)
            except ValueError:
                proxy_cls = ray_tpu.remote(
                    num_cpus=0.1, name=PROXY_NAME, namespace=NAMESPACE,
                    max_concurrency=64,
                )(HTTPProxy)
                proxy = proxy_cls.remote(http_host, http_port)
            _http_port = ray_tpu.get(proxy.get_port.remote(), timeout=30)
        ray_tpu.get(ctrl.status.remote(), timeout=30)  # wait alive
        _started = True


def http_port() -> Optional[int]:
    return _http_port


def _collect_specs(app: Application, route_prefix: str,
                   specs: List[dict]) -> dict:
    """Depth-first: nested bound deployments become handles."""
    dep = app.deployment
    init_args = []
    for a in app.init_args:
        if isinstance(a, Application):
            child_spec = _collect_specs(a, None, specs)
            init_args.append(DeploymentHandle(child_spec["name"]))
        else:
            init_args.append(a)
    init_kwargs = {}
    for k, v in app.init_kwargs.items():
        if isinstance(v, Application):
            child_spec = _collect_specs(v, None, specs)
            init_kwargs[k] = DeploymentHandle(child_spec["name"])
        else:
            init_kwargs[k] = v
    ac = dep.autoscaling_config
    if isinstance(ac, dict):  # options(autoscaling_config={...}) raw dict
        ac = AutoscalingConfig(**ac)
    spec = {
        "name": dep.name,
        "deployment_def": cloudpickle.dumps(dep.func_or_class),
        "init_args": tuple(init_args),
        "init_kwargs": init_kwargs,
        "num_replicas": dep.num_replicas,
        "max_ongoing_requests": dep.max_ongoing_requests,
        "request_timeout_s": dep.request_timeout_s,
        "user_config": dep.user_config,
        "autoscaling_config": ac.to_dict() if ac else None,
        "ray_actor_options": dep.ray_actor_options,
        "route_prefix": route_prefix,
    }
    specs.append(spec)
    return spec


def run(app: Application, *, name: str = "default",
        route_prefix: str = "/", blocking_ready: bool = True,
        timeout: float = 120.0) -> DeploymentHandle:
    """Deploy an application; returns the ingress handle
    (reference: `serve/api.py:414`)."""
    import ray_tpu

    if isinstance(app, Deployment):
        app = app.bind()
    start()
    specs: List[dict] = []
    ingress = _collect_specs(app, route_prefix, specs)
    ctrl = _controller()
    ray_tpu.get(ctrl.deploy.remote(specs), timeout=30)
    if blocking_ready:
        from ray_tpu.core.exceptions import TaskError

        for spec in specs:
            try:
                ok = ray_tpu.get(
                    ctrl.wait_ready.remote(spec["name"], timeout),
                    timeout=timeout + 10)
            except TaskError as e:
                # controller raises when the deployment went unhealthy
                # (e.g. replica constructor keeps failing)
                raise RuntimeError(str(e)) from None
            if not ok:
                raise TimeoutError(
                    f"deployment {spec['name']!r} not ready in {timeout}s")
    # push routes to the proxy
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME, namespace=NAMESPACE)
        routing = ray_tpu.get(ctrl.get_routing.remote(), timeout=10)
        ray_tpu.get(proxy.update_routes.remote(routing["routes"]), timeout=10)
    except ValueError:
        pass  # proxy-less mode
    # Application record (GCS KV): app name -> its deployment names, so
    # delete()/status by APP name works from any process (reference:
    # application-level state in the serve controller).
    import json as _json

    from ray_tpu.core.worker import global_worker

    global_worker().kv_put(
        name.encode(),
        _json.dumps([s["name"] for s in specs]).encode(),
        namespace="serve_apps")
    return DeploymentHandle(ingress["name"])


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


get_app_handle = get_deployment_handle


def status() -> dict:
    import ray_tpu

    return ray_tpu.get(_controller().status.remote(), timeout=10)


def delete(name: str):
    """Delete by APPLICATION name (removing all its deployments) or by a
    single deployment name."""
    import json as _json

    import ray_tpu
    from ray_tpu.core.worker import global_worker

    w = global_worker()
    raw = w.kv_get(name.encode(), namespace="serve_apps")
    if raw is not None:
        ok = True
        for dep in _json.loads(raw):
            ok = ray_tpu.get(
                _controller().delete_deployment.remote(dep),
                timeout=30) and ok
        w.kv_del(name.encode(), namespace="serve_apps")
        _push_routes_to_proxy()
        return ok
    result = ray_tpu.get(_controller().delete_deployment.remote(name),
                         timeout=30)
    _push_routes_to_proxy()
    return result


def _push_routes_to_proxy():
    """Sync the proxy's route table with the controller (the proxy holds a
    pushed copy; deletions must push too, or stale prefixes route to dead
    deployments and hang instead of 404ing)."""
    import ray_tpu

    try:
        proxy = ray_tpu.get_actor(PROXY_NAME, namespace=NAMESPACE)
        routing = ray_tpu.get(_controller().get_routing.remote(), timeout=10)
        ray_tpu.get(proxy.update_routes.remote(routing["routes"]),
                    timeout=10)
    except Exception:  # noqa: BLE001 — proxy-less mode / teardown races
        pass


def shutdown():
    global _started, _http_port
    import ray_tpu

    with _state_lock:
        # No early-exit on _started: a FRESH process (the `serve shutdown`
        # CLI) must still be able to tear down a live serve instance on
        # the cluster it attached to.
        if not ray_tpu.is_initialized():
            return
        try:
            ray_tpu.get(_controller().shutdown.remote(), timeout=30)
            ray_tpu.kill(_controller())
        except Exception:  # noqa: BLE001
            pass
        try:
            proxy = ray_tpu.get_actor(PROXY_NAME, namespace=NAMESPACE)
            ray_tpu.get(proxy.shutdown.remote(), timeout=10)
            ray_tpu.kill(proxy)
        except Exception:  # noqa: BLE001
            pass
        _started = False
        _http_port = None


def run_config(config, *, blocking: bool = False):
    """Deploy applications from a declarative config (reference: the
    ``serve deploy`` YAML schema, `python/ray/serve/schema.py` —
    ``applications: [{name, route_prefix, import_path}]``).  ``config``
    is a dict, a YAML/JSON file path, or a YAML string; each
    ``import_path`` is ``"module:attr"`` resolving to an Application (a
    bound deployment) or a Deployment (bound with no args).
    """
    import importlib
    import os as _os

    if isinstance(config, str):
        import yaml

        if _os.path.exists(config):
            with open(config) as f:
                config = yaml.safe_load(f)
        else:
            config = yaml.safe_load(config)
    apps = config.get("applications", [])
    if not apps:
        raise ValueError("config has no applications")
    out = []
    for app_cfg in apps:
        module_name, _, attr = app_cfg["import_path"].partition(":")
        target = getattr(importlib.import_module(module_name), attr)
        if not isinstance(target, (Application, Deployment)):
            raise TypeError(
                f"{app_cfg['import_path']} resolved to {type(target)}; "
                "expected an Application (deployment.bind()) or Deployment")
        # run() normalizes Deployment -> Application
        out.append(run(
            target,
            name=app_cfg.get("name", attr),
            route_prefix=app_cfg.get("route_prefix", "/"),
        ))
    if blocking:
        import time as _time

        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return out
