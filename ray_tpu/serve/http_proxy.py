"""HTTP ingress — a proxy actor running a threaded stdlib HTTP server.

Reference analogue: `python/ray/serve/_private/http_proxy.py:873`
(``HTTPProxyActor`` hosting uvicorn+ASGI).  TPU-image constraint: no
uvicorn/starlette wheels are guaranteed, so ingress is
``http.server.ThreadingHTTPServer`` — each request thread routes through
a DeploymentHandle (power-of-two-choices) and blocks on the replica
response; JSON in, JSON out.

Routes: ``POST/GET <route_prefix>`` dispatches to the app bound at that
prefix (longest-prefix match); ``GET /-/routes`` lists the table;
``GET /-/healthz`` liveness.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

PROXY_NAME = "SERVE_PROXY"
_SENTINEL = object()


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        from ray_tpu.serve.router import DeploymentHandle

        self._host = host
        self._port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, str] = {}
        self._lock = threading.Lock()
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self, body: Optional[bytes]):
                import time as _time

                from urllib.parse import parse_qs

                from ray_tpu.core.exceptions import (
                    BackPressureError,
                    DeadlineExceededError,
                )

                query = (self.path.split("?", 1) + [""])[1]
                # model id: header (reference contract) or query param
                model_id = self.headers.get(
                    "serve_multiplexed_model_id",
                    parse_qs(query).get("model_id", [""])[0])
                if parse_qs(query).get("stream", ["0"])[0] == "1":
                    return self._dispatch_stream(body, model_id)
                retry_after = None
                t0 = _time.perf_counter()
                try:
                    status, payload = proxy._handle(self.path, body, model_id)
                except BackPressureError as e:
                    # graceful degradation: every replica rejected through
                    # the router's retry budget — shed with 503 and tell
                    # the client when to come back (reference: Serve
                    # overload 503s instead of queueing to death)
                    status, payload = 503, json.dumps(
                        {"error": str(e), "retry_after_s": 1}).encode()
                    retry_after = "1"
                except DeadlineExceededError as e:
                    status, payload = 504, json.dumps(
                        {"error": str(e)}).encode()
                except Exception as e:  # noqa: BLE001
                    status, payload = 500, json.dumps(
                        {"error": str(e)}).encode()
                proxy._observe(self.path, status,
                               _time.perf_counter() - t0)
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                if retry_after is not None:
                    self.send_header("Retry-After", retry_after)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _dispatch_stream(self, body: Optional[bytes],
                                 model_id: str = ""):
                """?stream=1: chunked NDJSON, one line per yielded item —
                items flush as the replica produces them (streaming
                generator returns underneath)."""
                import time as _time

                t0 = _time.perf_counter()
                try:
                    items = proxy._handle_stream(self.path, body, model_id)
                    first = next(items, _SENTINEL)
                except Exception as e:  # noqa: BLE001
                    proxy._observe(self.path, 500,
                                   _time.perf_counter() - t0)
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                proxy._observe(self.path, 200, _time.perf_counter() - t0)
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(data: bytes):
                    self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))
                    self.wfile.flush()

                try:
                    if first is not _SENTINEL:
                        chunk(json.dumps(first, default=str).encode() + b"\n")
                        for item in items:
                            chunk(json.dumps(item, default=str).encode()
                                  + b"\n")
                except Exception as e:  # noqa: BLE001 mid-stream failure
                    chunk(json.dumps({"error": str(e)}).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self._dispatch(self.rfile.read(n) if n else None)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="serve-http-proxy",
                                        daemon=True)
        self._thread.start()

    # ----------------------------------------------------------------

    def _observe(self, path: str, status: int, seconds: float):
        """Ingress series, labeled by MATCHED route (bounded cardinality
        — arbitrary request paths never become label values)."""
        try:
            from ray_tpu.serve.telemetry import serve_metrics

            match = self._match_route(path)
            route = match[0] if match else "unmatched"
            m = serve_metrics()
            m["http_requests"].inc(
                tags={"route": route, "status": str(status)})
            m["http_latency"].observe(seconds, tags={"route": route})
        except Exception:  # noqa: BLE001 — telemetry never fails a request
            pass

    def _handle(self, path: str, body: Optional[bytes],
                model_id: str = ""):
        path = path.split("?", 1)[0]
        if path == "/-/healthz":
            return 200, b'"ok"'
        if path == "/-/routes":
            with self._lock:
                return 200, json.dumps(self._routes).encode()
        match = self._match_route(path)
        if match is None:
            return 404, json.dumps({"error": f"no route for {path}"}).encode()
        deployment = match[1]
        handle = self._get_handle(deployment)
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        request = json.loads(body) if body else None
        # call() = submit + resolve with replica-reject retries; a
        # saturated deployment raises BackPressureError (mapped to 503 +
        # Retry-After by the dispatcher), an expired request_timeout_s
        # raises DeadlineExceededError (504)
        result = handle.call(request, timeout=120)
        return 200, json.dumps(result, default=str).encode()

    def _match_route(self, path: str):
        path = path.split("?", 1)[0]
        with self._lock:
            match = None
            for prefix, deployment in self._routes.items():
                if path == prefix or path.startswith(
                        prefix.rstrip("/") + "/") or prefix == "/":
                    if match is None or len(prefix) > len(match[0]):
                        match = (prefix, deployment)
        return match

    def _handle_stream(self, path: str, body: Optional[bytes],
                       model_id: str = ""):
        """Yield the deployment's streamed items (resolved values)."""
        import ray_tpu

        match = self._match_route(path)
        if match is None:
            raise ValueError(f"no route for {path}")
        handle = self._get_handle(match[1], stream=True)
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        request = json.loads(body) if body else None
        for ref in handle.remote(request):
            yield ray_tpu.get(ref, timeout=120)

    def _get_handle(self, deployment: str, stream: bool = False):
        from ray_tpu.serve.router import DeploymentHandle

        key = (deployment, stream)
        with self._lock:
            h = self._handles.get(key)
            if h is None:
                h = self._handles[key] = DeploymentHandle(
                    deployment, stream=stream)
            return h

    # ---------------------------------------------------------------- ctrl

    def update_routes(self, routes: Dict[str, str]):
        with self._lock:
            self._routes = dict(routes)
        return True

    def get_port(self) -> int:
        return self._port

    def check_health(self) -> bool:
        return True

    def shutdown(self):
        self._server.shutdown()
        return True
