"""Serve data-plane telemetry — label-structured internal series.

Reference analogue: `python/ray/serve/_private/metrics_utils.py` and the
per-deployment ``serve_*`` Prometheus families the reference exports
(QPS, admission outcomes, latency, queue depths).  All series here are
internal-prefixed but REGISTERED with the per-process flusher
(``internal_metric(register=True)``): Serve's data plane runs in ordinary
driver/worker processes, so export rides the normal route — metrics KV
for /metrics, delta points for the GCS time-series table (range / rate /
quantile queries, SLO burn-rate alerting on the shed ratio).

Created lazily on first touch: importing serve must not start the
metrics flusher in processes that never serve traffic.
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ["serve_metrics", "set_replica_identity", "replica_identity"]

_lock = threading.Lock()
_m: Dict[str, object] = {}  # guard: _lock (filled once, then read-only)

#: This process's replica identity (one replica actor per worker process)
#: — lets in-replica code (batcher, stream TTFT) tag series without
#: threading names through every call.
_identity = {"deployment": "", "replica": ""}

_LATENCY_BOUNDS = (0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
_BATCH_BOUNDS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def set_replica_identity(deployment: str, replica: str):
    _identity["deployment"] = deployment
    _identity["replica"] = replica


def replica_identity() -> dict:
    return dict(_identity)


def serve_metrics() -> Dict[str, object]:
    """The Serve series, created (and flusher-registered) on first use."""
    # unguarded-ok: double-checked fast path — _m is populated exactly
    # once (one update() under _lock) and only read afterwards
    if _m:
        return _m  # unguarded-ok: see above
    with _lock:
        if _m:
            return _m
        from ray_tpu.util.metrics import (
            Counter,
            Gauge,
            Histogram,
            internal_metric,
        )

        made = {
            "requests": internal_metric(
                Counter, "ray_tpu_internal_serve_requests_total",
                "Requests offered to a deployment (router-observed: "
                "every call()/remote(), including ones later shed).",
                ("deployment",), register=True),
            "admitted": internal_metric(
                Counter, "ray_tpu_internal_serve_admitted_total",
                "Dispatch attempts that passed router-side admission.",
                ("deployment",), register=True),
            "shed": internal_metric(
                Counter, "ray_tpu_internal_serve_shed_total",
                "Requests shed with BackPressureError after the "
                "reject-retry budget was exhausted.",
                ("deployment",), register=True),
            "retries": internal_metric(
                Counter, "ray_tpu_internal_serve_retries_total",
                "Re-pick attempts after a full-replica reject.",
                ("deployment",), register=True),
            "latency": internal_metric(
                Histogram, "ray_tpu_internal_serve_request_latency_s",
                "End-to-end call() latency (admission + replica "
                "execution + resolve).",
                boundaries=_LATENCY_BOUNDS, tag_keys=("deployment",),
                register=True),
            "ttft": internal_metric(
                Histogram, "ray_tpu_internal_serve_ttft_s",
                "Time from stream-request entry to the first yielded "
                "item.",
                boundaries=_LATENCY_BOUNDS, tag_keys=("deployment",),
                register=True),
            "batch": internal_metric(
                Histogram, "ray_tpu_internal_serve_batch_size",
                "Formed @serve.batch sizes.",
                boundaries=_BATCH_BOUNDS, tag_keys=("deployment",),
                register=True),
            "inflight": internal_metric(
                Gauge, "ray_tpu_internal_serve_replica_inflight",
                "In-flight (admitted, executing) requests on a replica.",
                ("deployment", "replica"), register=True),
            "queue": internal_metric(
                Gauge, "ray_tpu_internal_serve_replica_queue_depth",
                "Requests parked in this replica's @serve.batch queues.",
                ("deployment", "replica"), register=True),
            "http_requests": internal_metric(
                Counter, "ray_tpu_internal_serve_http_requests_total",
                "HTTP proxy responses by matched route and status code.",
                ("route", "status"), register=True),
            "http_latency": internal_metric(
                Histogram, "ray_tpu_internal_serve_http_latency_s",
                "HTTP proxy end-to-end latency by matched route.",
                boundaries=_LATENCY_BOUNDS, tag_keys=("route",),
                register=True),
        }
        _m.update(made)
    return _m  # unguarded-ok: populated above; read-only once non-empty
