"""DeploymentHandle — routes requests to replicas, power-of-two-choices.

Reference analogues: `python/ray/serve/handle.py:86` (``RayServeHandle``),
`serve/_private/router.py:244` (``PowerOfTwoChoicesReplicaScheduler``:
sample two replicas, probe queue lengths, pick the shorter queue —
`:639,856`).  Config PUSH: a background listener long-polls the controller
(`listen_for_change`, the `_private/long_poll.py:187` analogue), so a
redeploy updates every handle the moment the routing version bumps — no
staleness window.
"""

from __future__ import annotations

import random
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import ray_tpu.serve.replica  # noqa: F401 — defines serve_backpressure
from ray_tpu.core.config import config
from ray_tpu.serve.controller import CONTROLLER_NAME, NAMESPACE

config.define("serve_probe_timeout_s", float, 1.0,
              "Queue-length probe timeout on the request routing path.  "
              "Was 5 s: a dead or partitioned replica then stalled every "
              "request that sampled it for the full window; with "
              "suspicion-based liveness a short probe plus immediate "
              "local exclusion re-picks in about a second worst-case.")
config.define("serve_reject_retry_budget", int, 3,
              "Per-request retry budget when a replica rejects with "
              "BackPressureError (max_ongoing_requests admission): the "
              "router re-picks another replica up to this many times "
              "(jittered backoff between attempts) before shedding the "
              "request — HTTP 503 + Retry-After on the proxy.")


class _DeploymentRouting:
    """Process-wide routing cache for ONE deployment, fed by a single
    long-poll listener thread — every DeploymentHandle (and every
    ``.options()`` copy) shares it, so N handles cost one parked
    ``listen_for_change`` call on the controller, not N."""

    def __init__(self, deployment: str):
        self.deployment = deployment
        self.lock = threading.Lock()
        self.replicas: List[Any] = []
        self.fetched = False
        self.version = -1
        self.request_timeout_s: Optional[float] = None
        self.max_ongoing = 0  # guard: lock
        # Router-side in-flight count per replica (reference: the Serve
        # router tracks its own per-replica in-flight and never
        # over-dispatches): ``call()`` claims a slot BEFORE submitting,
        # so an overloaded deployment rejects at the router in
        # microseconds instead of the request queueing replica-side.
        self.inflight: Dict[Any, int] = {}  # guard: lock
        self._listener: Optional[threading.Thread] = None

    def _controller(self):
        import ray_tpu

        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=NAMESPACE)

    def apply(self, routing: dict):
        import ray_tpu

        entry = routing["deployments"].get(self.deployment)
        if entry is None:
            raise ValueError(f"no deployment named {self.deployment!r}")
        handles = [ray_tpu.get_actor(n, namespace=NAMESPACE)
                   for n in entry["replicas"]]
        with self.lock:
            self.replicas = handles
            self.fetched = True
            self.version = routing["version"]
            self.request_timeout_s = entry.get("request_timeout_s")
            self.max_ongoing = int(entry.get("max_ongoing_requests") or 0)

    def refresh(self, force: bool = False):
        import ray_tpu

        with self.lock:
            if not force and self.fetched:
                return
        self.apply(
            ray_tpu.get(self._controller().get_routing.remote(), timeout=10))
        self.ensure_listener()

    def ensure_listener(self):
        with self.lock:
            if self._listener is not None and self._listener.is_alive():
                return
            self._listener = threading.Thread(
                target=self._listen_loop, name=f"serve-lp-{self.deployment}",
                daemon=True)
            self._listener.start()

    def _listen_loop(self):
        """Push channel: parked on the controller until the routing version
        moves; an idle timeout just re-issues the poll."""
        import ray_tpu

        while True:
            try:
                routing = ray_tpu.get(
                    self._controller().listen_for_change.remote(
                        self.version, 30.0),
                    timeout=45)
                if routing["deployments"].get(self.deployment) is None:
                    with _routing_lock:
                        _routing.pop(self.deployment, None)
                    _prune_affinity(self.deployment)
                    return  # deployment deleted: stop listening
                self.apply(routing)
            except Exception:  # noqa: BLE001 controller restart/teardown
                time.sleep(0.2)
                try:
                    self._controller()
                except Exception:  # noqa: BLE001 serve is gone
                    with _routing_lock:
                        _routing.pop(self.deployment, None)
                    return


_routing: dict = {}
_routing_lock = threading.Lock()

#: Short-TTL cache of cluster liveness for the routing hot path: node ids
#: that are SUSPECT (missed heartbeats, probe pending) or dead.  Replicas
#: hosted there are excluded from picks immediately — routing around a
#: suspect costs nothing, while probing into one costs a timeout.
_unhealthy_nodes_cache: dict = {"at": 0.0, "nodes": frozenset()}
_unhealthy_nodes_lock = threading.Lock()
_UNHEALTHY_TTL_S = 1.0


def _unhealthy_nodes() -> frozenset:
    now = time.monotonic()
    with _unhealthy_nodes_lock:
        if now - _unhealthy_nodes_cache["at"] < _UNHEALTHY_TTL_S:
            return _unhealthy_nodes_cache["nodes"]
        _unhealthy_nodes_cache["at"] = now  # claim the refresh window
    try:
        from ray_tpu.core.worker import global_worker

        nodes = frozenset(
            n["node_id"] for n in global_worker().gcs_nodes()
            if not n.get("alive", True) or n.get("suspect")
            or n.get("draining"))
    except Exception:  # noqa: BLE001 — liveness view is best-effort
        nodes = frozenset()
    with _unhealthy_nodes_lock:
        _unhealthy_nodes_cache["nodes"] = nodes
    return nodes


def _replica_nodes(replicas) -> dict:
    """Map replica handle -> hosting node id via the actor table (one GCS
    round trip, only consulted when some node is unhealthy)."""
    try:
        from ray_tpu.core.worker import global_worker

        w = global_worker()
        if w.mode == "driver":
            table = w.raylet.gcs.list_actors()
        elif w.mode == "client":
            table = w.gcs.list_actors()
        elif w.mode == "worker":
            table = w._request("gcs_list_actors")
        else:
            return {}
        by_id = {a["actor_id"]: a.get("exec_node") or a.get("owner_node")
                 for a in table}
        return {r: by_id.get(r._actor_id.hex()) for r in replicas}
    except Exception:  # noqa: BLE001
        return {}


def _routing_for(deployment: str) -> _DeploymentRouting:
    with _routing_lock:
        entry = _routing.get(deployment)
        if entry is None:
            entry = _routing[deployment] = _DeploymentRouting(deployment)
        return entry


#: (deployment, model_id) -> replica handle that served it last.  Model
#: affinity for multiplexed deployments (reference: the router's
#: multiplexed-model-id replica ranking): repeat requests for the same
#: model prefer the replica that already has it loaded.  Bounded LRU: a
#: rotating model-id space must not grow process memory forever.
_model_affinity: "OrderedDict" = OrderedDict()
_model_affinity_lock = threading.Lock()
_MODEL_AFFINITY_CAP = 4096
#: replica -> (queue_len, ts): short-TTL cache of the affinity probe so the
#: multiplexed hot path doesn't pay a round trip per request
_affinity_probe_cache: "OrderedDict" = OrderedDict()
_AFFINITY_PROBE_TTL_S = 1.0


def _prune_affinity(deployment: str):
    """Drop every affinity entry of a deleted deployment — entries (and
    their dead replica handles) would otherwise accumulate forever across
    deploy/delete cycles."""
    with _model_affinity_lock:
        for key in [k for k in _model_affinity if k[0] == deployment]:
            del _model_affinity[key]


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 stream: bool = False, multiplexed_model_id: str = ""):
        self._deployment = deployment_name
        self._method = method_name
        self._stream = stream
        self._model_id = multiplexed_model_id

    # ------------------------------------------------------------- plumbing

    @property
    def _routing(self) -> _DeploymentRouting:
        return _routing_for(self._deployment)

    def _refresh(self, force: bool = False):
        self._routing.refresh(force)

    def _exclude_replicas(self, bad: List[Any]):
        """Drop failed replicas from the SHARED routing table immediately
        — every handle of this deployment skips them until the next
        controller push re-asserts membership."""
        if not bad:
            return
        routing = self._routing
        with routing.lock:
            routing.replicas = [r for r in routing.replicas
                                if r not in bad]

    def _live_replicas(self):
        """Current replica set minus SUSPECT/dead/draining hosts.  The
        liveness filter is advisory: when it would empty the set (every
        host suspect — likely a detector blip) the unfiltered set wins,
        availability over purity."""
        routing = self._routing
        self._refresh()
        with routing.lock:
            replicas = list(routing.replicas)
        deadline = time.time() + 30.0
        while not replicas:
            if time.time() > deadline:
                raise RuntimeError(
                    f"deployment {self._deployment!r} has no ready replicas")
            time.sleep(0.1)
            self._refresh(force=True)
            with routing.lock:
                replicas = list(routing.replicas)
        unhealthy = _unhealthy_nodes()
        if unhealthy:
            hosts = _replica_nodes(replicas)
            healthy = [r for r in replicas
                       if hosts.get(r) not in unhealthy]
            if healthy:
                return healthy
        return replicas

    def _pick_replica(self):
        """Power-of-two-choices (reference `router.py:639`): sample two,
        probe in-flight counts, route to the less loaded.  Probes are
        SHORT (serve_probe_timeout_s, default 1 s — was a routing-stalling
        5 s) and a probe failure excludes the replica from the shared
        routing table immediately before re-picking; replicas on SUSPECT
        hosts are never sampled in the first place."""
        import ray_tpu

        timeout = max(0.1, config.serve_probe_timeout_s)
        for _attempt in range(3):
            replicas = self._live_replicas()
            if len(replicas) == 1:
                a, b = replicas[0], None
            else:
                a, b = random.sample(replicas, 2)
            pair = [a] if b is None else [a, b]
            refs = [r.get_queue_len.remote() for r in pair]
            try:
                if b is None:
                    ray_tpu.get(refs[0], timeout=timeout)
                    return a
                qa, qb = ray_tpu.get(refs, timeout=timeout)
                return a if qa <= qb else b
            except Exception:  # noqa: BLE001 — dead/stale/stalled replica
                # Identify the failure (the batched get hides which ref
                # errored): anything not resolved within a grace beat is
                # treated as dead and excluded NOW — later controller
                # pushes re-add survivors.
                done, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                       timeout=0.2)
                bad = []
                for r, ref in zip(pair, refs):
                    if ref not in done:
                        bad.append(r)
                        continue
                    try:
                        ray_tpu.get(ref, timeout=0.1)
                    except Exception:  # noqa: BLE001
                        bad.append(r)
                # NOT followed by a forced refresh: a refetch would just
                # re-add the corpse from the controller's not-yet-updated
                # table — the exclusion stands until the next controller
                # PUSH re-asserts membership (and _live_replicas force-
                # refreshes on its own if the set empties).  An empty
                # ``bad`` means every probe resolved fine, just late
                # (loaded-but-healthy replicas): retry without evicting.
                self._exclude_replicas(bad)
        # three strikes: hand out an unprobed member rather than failing —
        # the call itself surfaces the error if the replica is truly gone
        replicas = self._live_replicas()
        return random.choice(replicas)

    # ------------------------------------------------------------- calling

    def _pick_replica_affine(self):
        """Model affinity: prefer the replica that last served this model
        (it has the model in its LRU) unless it is heavily loaded relative
        to a power-of-two alternative.

        The affinity probe is cached (~1s TTL) and short-timeout: the
        reference pushes loaded-model ids to the router instead of probing,
        so a per-request synchronous 5s probe on the hot path — blocking
        a full 5s whenever the cached replica just died — was the wrong
        trade.  A stale-but-fresh queue length only risks a slightly
        suboptimal pick; a dead replica costs at most 0.5s once per TTL."""
        import ray_tpu

        key = (self._deployment, self._model_id)
        with _model_affinity_lock:
            cached = _model_affinity.get(key)
            if cached is not None:
                _model_affinity.move_to_end(key)
        routing = self._routing
        self._refresh()
        with routing.lock:
            alive = set(routing.replicas)
        if cached is not None and cached in alive:
            now = time.time()
            with _model_affinity_lock:
                probe = _affinity_probe_cache.get(cached)
            if probe is not None and now - probe[1] < _AFFINITY_PROBE_TTL_S:
                if probe[0] <= 4:
                    return cached
            else:
                try:
                    q = ray_tpu.get(cached.get_queue_len.remote(),
                                    timeout=0.5)
                    with _model_affinity_lock:
                        _affinity_probe_cache[cached] = (q, now)
                        while len(_affinity_probe_cache) > \
                                _MODEL_AFFINITY_CAP:
                            _affinity_probe_cache.popitem(last=False)
                    if q <= 4:  # loaded-model locality beats a cold load
                        return cached
                except Exception:  # noqa: BLE001 — replica gone
                    pass
        replica = self._pick_replica()
        with _model_affinity_lock:
            _model_affinity[key] = replica
            while len(_model_affinity) > _MODEL_AFFINITY_CAP:
                _model_affinity.popitem(last=False)
        return replica

    def remote(self, request: Any = None, _replica: Any = None,
               _counted: bool = False):
        """Dispatch; returns an ObjectRef (resolve with ray_tpu.get), or an
        ObjectRefGenerator when the handle has ``stream=True``."""
        from ray_tpu.serve.telemetry import serve_metrics
        from ray_tpu.util import tracing

        if not _counted:
            # offered-load series (call() already counted its request —
            # including attempts that shed before ever dispatching)
            serve_metrics()["requests"].inc(
                tags={"deployment": self._deployment})
        if not tracing.tracing_enabled():
            return self._remote_inner(request, _replica)
        # router→replica hop: the serve request's root span (or a child,
        # when the handle call itself runs inside a traced request) —
        # replica pick + probes + the actor-call submit all parent here,
        # so the routing cost is visible next to replica execution time
        with tracing.span(f"serve.route {self._deployment}",
                          method=self._method, stream=self._stream):
            return self._remote_inner(request, _replica)

    def _remote_inner(self, request: Any, _replica: Any = None):
        if _replica is not None:
            replica = _replica  # slot-claimed by call() — must dispatch
            # to the replica the slot was charged to, or the inflight map
            # drifts from real placement
        elif self._model_id:
            replica = self._pick_replica_affine()
        else:
            replica = self._pick_replica()
        # Deadline stamp (Serve request_timeout_s): the replica call — and
        # everything it fans out to — inherits an absolute deadline;
        # expiry anywhere sheds/interrupts instead of running on forever.
        timeout_s = self._routing.request_timeout_s
        if self._stream:
            method = replica.handle_request_stream.options(
                num_returns="streaming")
            if timeout_s is not None and config.deadlines:
                method = method.options(deadline_s=timeout_s)
            return method.remote(request, self._method, self._model_id)
        method = replica.handle_request
        if timeout_s is not None and config.deadlines:
            method = method.options(deadline_s=timeout_s)
        return method.remote(request, self._method, self._model_id)

    def _acquire_slot(self):
        """Router-side admission: claim the least-loaded live replica
        still below ``max_ongoing_requests`` AS COUNTED BY THIS ROUTER
        (reference: the Serve router tracks per-replica in-flight and
        never over-dispatches).  Returns the claimed replica, None when
        every replica is full (caller backs off / sheds — the request
        never queues replica-side, which is what keeps admitted p99
        bounded under overload), or the sentinel False when admission is
        unenforced (no cap / kill switch) and the caller should use the
        legacy probe-based pick."""
        routing = self._routing
        self._refresh()
        with routing.lock:
            cap = routing.max_ongoing
        if cap <= 0 or not config.serve_backpressure or self._model_id:
            # unenforced (no cap / kill switch), or a multiplexed request
            # — model affinity picks its own replica, so a slot charged
            # to the least-loaded one would just drift the inflight map;
            # multiplexed calls rely on the replica-side gate
            return False
        replicas = self._live_replicas()
        # NOT pruned against the live set: a probe-suspected replica's
        # in-flight work is still running — resetting its count to zero
        # on recovery would over-admit; entries self-clean because every
        # claim's finally releases (pop at count<=1)
        with routing.lock:
            if not replicas:
                return None
            count, _, best = min(
                (routing.inflight.get(r, 0), i, r)
                for i, r in enumerate(replicas))
            if count >= cap:
                return None
            routing.inflight[best] = count + 1
        return best

    def _release_slot(self, replica):
        routing = self._routing
        with routing.lock:
            count = routing.inflight.get(replica, 0)
            if count > 1:
                routing.inflight[replica] = count - 1
            else:
                routing.inflight.pop(replica, None)

    def call(self, request: Any = None, timeout: Optional[float] = None):
        """Submit AND resolve, under router-side admission: a slot on the
        least-loaded replica is claimed BEFORE submitting (so an
        overloaded deployment rejects in microseconds at the router —
        the request never sits in a replica queue inflating its
        latency), re-tried under a per-request budget (jittered backoff
        from ``util/retry.py``, short — the wait is for an in-flight
        request to finish); when every attempt finds all replicas full —
        the deployment is saturated — the request is SHED with a typed
        ``BackPressureError`` (HTTP proxy: 503 + Retry-After).  The
        replica-side ``max_ongoing_requests`` check stays as the
        authoritative gate (other routers/drivers race this one); plain
        ``.remote()`` callers observe those rejects at ``get()``."""
        import ray_tpu
        from ray_tpu.core.exceptions import BackPressureError
        from ray_tpu.serve.telemetry import serve_metrics
        from ray_tpu.util.retry import BackoffPolicy

        m = serve_metrics()
        tags = {"deployment": self._deployment}
        m["requests"].inc(tags=tags)
        t_start = time.perf_counter()
        budget = max(0, config.serve_reject_retry_budget)
        backoff = BackoffPolicy(base_s=0.01, max_s=0.25)
        last: Optional[BackPressureError] = None
        for attempt in range(budget + 1):
            if attempt:
                m["retries"].inc(tags=tags)
                time.sleep(backoff.delay(attempt - 1))
            slot = self._acquire_slot()
            if slot is None:
                last = BackPressureError(
                    f"all replicas of {self._deployment!r} at "
                    f"max_ongoing_requests")
                continue
            m["admitted"].inc(tags=tags)
            try:
                result = ray_tpu.get(
                    self.remote(request, _replica=slot or None,
                                _counted=True),
                    timeout=timeout)
                m["latency"].observe(time.perf_counter() - t_start,
                                     tags=tags)
                return result
            except BackPressureError as e:
                last = e  # replica-side race (another router's traffic)
            finally:
                if slot is not False:
                    self._release_slot(slot)
        m["shed"].inc(tags=tags)
        raise BackPressureError(
            f"deployment {self._deployment!r} saturated: "
            f"{budget + 1} attempts all rejected ({last})")

    def options(self, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                ) -> "DeploymentHandle":
        return DeploymentHandle(
            self._deployment,
            self._method if method_name is None else method_name,
            self._stream if stream is None else stream,
            self._model_id if multiplexed_model_id is None
            else multiplexed_model_id)

    @property
    def method(self):
        """``handle.method.<name>.remote(x)`` sugar."""
        return _MethodNamespace(self)

    def __reduce__(self):
        return (DeploymentHandle, (self._deployment, self._method,
                                   self._stream, self._model_id))

    def __repr__(self):
        return f"DeploymentHandle({self._deployment!r})"


class _MethodNamespace:
    def __init__(self, handle: DeploymentHandle):
        self._handle = handle

    def __getattr__(self, name):
        return DeploymentHandle(self._handle._deployment, name)
