"""DeploymentHandle — routes requests to replicas, power-of-two-choices.

Reference analogues: `python/ray/serve/handle.py:86` (``RayServeHandle``),
`serve/_private/router.py:244` (``PowerOfTwoChoicesReplicaScheduler``:
sample two replicas, probe queue lengths, pick the shorter queue —
`:639,856`).  Config push is poll-based here (the reference long-polls,
`_private/long_poll.py`): handles refresh their replica set from the
controller when stale or on miss.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, List, Optional

from ray_tpu.serve.controller import CONTROLLER_NAME, NAMESPACE

_REFRESH_S = 1.0


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self._deployment = deployment_name
        self._method = method_name
        self._lock = threading.Lock()
        self._replicas: List[Any] = []  # ActorHandles
        self._fetched_at = 0.0
        self._version = -1

    # ------------------------------------------------------------- plumbing

    def _controller(self):
        import ray_tpu

        return ray_tpu.get_actor(CONTROLLER_NAME, namespace=NAMESPACE)

    def _refresh(self, force: bool = False):
        import ray_tpu

        now = time.time()
        with self._lock:
            if not force and self._replicas and \
                    now - self._fetched_at < _REFRESH_S:
                return
        routing = ray_tpu.get(self._controller().get_routing.remote(),
                              timeout=10)
        entry = routing["deployments"].get(self._deployment)
        if entry is None:
            raise ValueError(
                f"no deployment named {self._deployment!r}")
        handles = [ray_tpu.get_actor(n, namespace=NAMESPACE)
                   for n in entry["replicas"]]
        with self._lock:
            self._replicas = handles
            self._fetched_at = now
            self._version = routing["version"]

    def _pick_replica(self):
        """Power-of-two-choices (reference `router.py:639`): sample two,
        probe in-flight counts, route to the less loaded."""
        import ray_tpu

        self._refresh()
        with self._lock:
            replicas = list(self._replicas)
        deadline = time.time() + 30.0
        while not replicas:
            if time.time() > deadline:
                raise RuntimeError(
                    f"deployment {self._deployment!r} has no ready replicas")
            time.sleep(0.1)
            self._refresh(force=True)
            with self._lock:
                replicas = list(self._replicas)
        if len(replicas) == 1:
            a, b = replicas[0], None
        else:
            a, b = random.sample(replicas, 2)
        # The probe doubles as a liveness check: a cached-but-dead replica
        # (e.g. just replaced by an in-place redeploy) errors here and we
        # refetch the table instead of handing the caller a dead ref.
        try:
            if b is None:
                ray_tpu.get(a.get_queue_len.remote(), timeout=5.0)
                return a
            qa, qb = ray_tpu.get(
                [a.get_queue_len.remote(), b.get_queue_len.remote()],
                timeout=5.0)
        except Exception:  # noqa: BLE001 - stale replica: refetch, retry once
            self._refresh(force=True)
            with self._lock:
                replicas = list(self._replicas)
            if not replicas:
                raise RuntimeError(
                    f"deployment {self._deployment!r} lost its replicas")
            return random.choice(replicas)
        return a if qa <= qb else b

    # ------------------------------------------------------------- calling

    def remote(self, request: Any = None):
        """Dispatch; returns an ObjectRef (resolve with ray_tpu.get)."""
        replica = self._pick_replica()
        return replica.handle_request.remote(request, self._method)

    def options(self, method_name: str = "__call__") -> "DeploymentHandle":
        return DeploymentHandle(self._deployment, method_name)

    @property
    def method(self):
        """``handle.method.<name>.remote(x)`` sugar."""
        return _MethodNamespace(self)

    def __reduce__(self):
        return (DeploymentHandle, (self._deployment, self._method))

    def __repr__(self):
        return f"DeploymentHandle({self._deployment!r})"


class _MethodNamespace:
    def __init__(self, handle: DeploymentHandle):
        self._handle = handle

    def __getattr__(self, name):
        return DeploymentHandle(self._handle._deployment, name)
