"""ServeController — reconciles desired deployment state onto replicas.

Reference analogues: `python/ray/serve/controller.py:74`
(``ServeController`` + ``deploy_apps :587``),
`serve/_private/deployment_state.py` (replica reconciliation),
`serve/_private/autoscaling_policy.py:95` (``BasicAutoscalingPolicy`` —
queue-depth driven replica targets).

One named controller actor per runtime.  A background thread ticks
reconcile + autoscale; public methods mutate desired state under a lock.
Replicas are named actors (``SERVE_REPLICA::<deployment>#<uid>``) so
routers resolve them by name without shipping handles.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
NAMESPACE = "serve"
RECONCILE_INTERVAL_S = 0.25
_MAX_START_FAILURES = 3


def replica_actor_name(deployment: str, uid: int) -> str:
    return f"SERVE_REPLICA::{deployment}#{uid}"


class _ReplicaState:
    def __init__(self, name: str, handle, uid: int):
        self.name = name
        self.handle = handle
        self.uid = uid
        self.ready = False
        self.ready_ref = None
        self.health_ref = None  # outstanding liveness probe
        self.dead = False
        # rolling redeploy: old-version replicas keep serving until the new
        # ones are ready, then drain (killed once idle or after timeout)
        self.draining = False
        self.drain_since = None
        self.drain_probe = None


class _DeploymentState:
    def __init__(self, spec: dict):
        self.spec = spec
        self.replicas: List[_ReplicaState] = []
        self.next_uid = 0
        self.target = spec["num_replicas"]
        # autoscaler bookkeeping
        self.ongoing_ema = 0.0
        self.over_since: Optional[float] = None
        self.under_since: Optional[float] = None
        self.version = 0
        # consecutive replica-start failures; at _MAX_START_FAILURES the
        # deployment is marked unhealthy instead of respawn-looping
        self.start_failures = 0
        self.unhealthy_reason: Optional[str] = None
        self.flip_at: Optional[float] = None  # rollout traffic-flip time


class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        # long-poll listeners wake on every routing-version bump
        # (reference: LongPollHost, `serve/_private/long_poll.py:187`)
        self._change = threading.Condition(self._lock)
        self._deployments: Dict[str, _DeploymentState] = {}
        self._routes: Dict[str, str] = {}  # route_prefix -> deployment name
        self._version = 0
        self._shutdown = False
        self._thread = threading.Thread(target=self._control_loop,
                                        name="serve-controller",
                                        daemon=True)
        self._thread.start()

    # --------------------------------------------------------------- deploy

    def deploy(self, specs: List[dict]):
        """specs: [{name, deployment_def(blob), init_args, init_kwargs,
        num_replicas, max_ongoing_requests, user_config, route_prefix,
        autoscaling_config}]"""
        with self._lock:
            for spec in specs:
                name = spec["name"]
                existing = self._deployments.get(name)
                if existing is not None:
                    # In-place ROLLING update (reference: deployment_state
                    # rolling replica replacement): old replicas keep
                    # serving until new-version replicas are ready, then
                    # drain — requests never hit a just-killed replica and
                    # there is no empty-replica window.
                    existing.spec = spec
                    existing.target = self._initial_target(spec)
                    existing.flip_at = None
                    now = time.time()
                    for r in existing.replicas:
                        if not r.draining:
                            r.draining = True
                            r.drain_since = now
                    existing.version += 1
                else:
                    st = _DeploymentState(spec)
                    st.target = self._initial_target(spec)
                    self._deployments[name] = st
                if spec.get("route_prefix"):
                    self._routes[spec["route_prefix"]] = name
            self._version += 1
            self._change.notify_all()
        self._reconcile()
        return True

    def _initial_target(self, spec) -> int:
        ac = spec.get("autoscaling_config")
        if ac:
            return max(ac.get("min_replicas", 1),
                       min(spec["num_replicas"], ac.get("max_replicas", 1)))
        return spec["num_replicas"]

    def delete_deployment(self, name: str):
        with self._lock:
            st = self._deployments.pop(name, None)
            if st is None:
                return False
            for r in st.replicas:
                self._kill_replica(r)
            self._routes = {p: d for p, d in self._routes.items()
                            if d != name}
            self._version += 1
            self._change.notify_all()
        return True

    def shutdown(self):
        with self._lock:
            self._shutdown = True
            for st in self._deployments.values():
                for r in st.replicas:
                    self._kill_replica(r)
            self._deployments.clear()
            self._routes.clear()
        return True

    # --------------------------------------------------------------- queries

    def get_routing(self) -> dict:
        """Routing table for handles/proxies: deployment -> replica actor
        names (ready only), plus route prefixes and a version counter."""
        with self._lock:
            return {
                "version": self._version,
                "deployments": {
                    name: {
                        "replicas": self._serving_replica_names(st),
                        "max_ongoing_requests":
                            st.spec.get("max_ongoing_requests", 100),
                        "request_timeout_s":
                            st.spec.get("request_timeout_s"),
                    }
                    for name, st in self._deployments.items()
                },
                "routes": dict(self._routes),
            }

    def listen_for_change(self, known_version: int,
                          timeout: float = 30.0) -> dict:
        """Long-poll (reference: `serve/_private/long_poll.py:187`): block
        until the routing version moves past ``known_version`` (or the
        idle timeout lapses — the client just re-issues), then return the
        fresh routing table.  Handles learn of redeploys the instant they
        land instead of on a poll interval."""
        deadline = time.time() + timeout
        with self._change:
            while self._version == known_version and not self._shutdown:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._change.wait(remaining)
        return self.get_routing()

    def _serving_replica_names(self, st) -> list:
        # Blue/green flip: traffic moves to the new version only when its
        # FULL replica set is ready — a partial flip would funnel all
        # traffic through the first fresh replica while the rest start.
        fresh = [r.name for r in st.replicas
                 if r.ready and not r.dead and not r.draining]
        if len(fresh) >= st.target:
            return fresh
        # mid-rollout: the old version serves until the new one is up
        old = [r.name for r in st.replicas
               if r.ready and not r.dead and r.draining]
        return old if old else fresh

    def status(self) -> dict:
        with self._lock:
            return {
                name: {
                    "target": st.target,
                    "running": sum(1 for r in st.replicas
                                   if r.ready and not r.dead),
                    "starting": sum(1 for r in st.replicas if not r.ready),
                    "version": st.version,
                    "unhealthy": st.unhealthy_reason,
                }
                for name, st in self._deployments.items()
            }

    def wait_ready(self, name: str, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                st = self._deployments.get(name)
                if st is not None and st.unhealthy_reason is not None:
                    raise RuntimeError(
                        f"deployment {name!r} unhealthy: "
                        f"{st.unhealthy_reason}")
                if st is not None and st.target >= 1 and \
                        sum(1 for r in st.replicas if r.ready
                            and not r.dead and not r.draining) >= st.target:
                    return True
            time.sleep(0.05)
        return False

    # --------------------------------------------------------------- loop

    def _control_loop(self):
        tick = 0
        while not self._shutdown:
            try:
                self._reconcile()
                self._autoscale()
                if tick % 4 == 0:  # ~1s cadence
                    self._health_check()
            except Exception:  # noqa: BLE001 - keep the loop alive
                pass
            tick += 1
            time.sleep(RECONCILE_INTERVAL_S)

    def _health_check(self):
        """Probe ready replicas; mark the dead for reaping (reference:
        deployment_state health checks each tick).  Probes are
        fire-and-collect — never block the control loop on a replica."""
        import ray_tpu

        with self._lock:
            replicas = [r for st in self._deployments.values()
                        for r in st.replicas if r.ready and not r.dead]
        for r in replicas:
            if r.health_ref is None:
                r.health_ref = r.handle.check_health.remote()
                continue
            ready, _ = ray_tpu.wait([r.health_ref], num_returns=1, timeout=0)
            if not ready:
                continue  # busy replica; collect next pass
            try:
                ray_tpu.get(r.health_ref, timeout=1)
            except Exception:  # noqa: BLE001 - actor died
                r.dead = True
            r.health_ref = None

    def _reconcile(self):
        import ray_tpu
        from ray_tpu.serve.replica import Replica

        with self._lock:
            if self._shutdown:
                return
            for name, st in self._deployments.items():
                # mark started replicas ready — a resolved ready_ref can be
                # an ERROR (constructor raised): wait() reports errored
                # objects as "ready", so the get() is what distinguishes a
                # live replica from a dead one.
                for r in list(st.replicas):
                    if not r.ready and r.ready_ref is not None:
                        ready, _ = ray_tpu.wait([r.ready_ref], num_returns=1,
                                                timeout=0)
                        if not ready:
                            continue
                        try:
                            ray_tpu.get(r.ready_ref, timeout=1)
                        except Exception as e:  # noqa: BLE001
                            st.replicas.remove(r)
                            self._kill_replica(r)
                            st.start_failures += 1
                            if st.start_failures >= _MAX_START_FAILURES:
                                st.unhealthy_reason = (
                                    f"replica failed to start "
                                    f"{st.start_failures}x: {e!r}")
                            self._version += 1
                            self._change.notify_all()
                            continue
                        r.ready = True
                        r.ready_ref = None
                        st.start_failures = 0
                        st.unhealthy_reason = None
                        self._version += 1
                        self._change.notify_all()
                # reap ready replicas that died after startup (health probe
                # issued by _health_check; a dead actor errors its calls)
                for r in list(st.replicas):
                    if r.ready and getattr(r, "dead", False):
                        st.replicas.remove(r)
                        self._version += 1
                        self._change.notify_all()
                # drain old-version replicas once the new version serves
                self._reap_draining(st)
                # scale up (draining replicas don't count toward target)
                spec = st.spec
                if st.unhealthy_reason is not None:
                    continue
                active = [r for r in st.replicas if not r.draining]
                while len(active) < st.target:
                    uid = st.next_uid
                    st.next_uid += 1
                    actor_name = replica_actor_name(name, uid)
                    res = dict(spec.get("ray_actor_options") or {})
                    cls = ray_tpu.remote(
                        num_cpus=res.get("num_cpus", 1),
                        num_tpus=res.get("num_tpus", 0),
                        max_concurrency=max(
                            spec.get("max_ongoing_requests", 100), 8) + 4,
                        name=actor_name, namespace=NAMESPACE,
                    )(Replica)
                    handle = cls.remote(
                        spec["deployment_def"], spec.get("init_args") or (),
                        spec.get("init_kwargs") or {},
                        spec.get("user_config"),
                        # the replica enforces this by REJECTING beyond it
                        # (typed BackPressureError; router retries/sheds)
                        spec.get("max_ongoing_requests", 100),
                        deployment_name=name, replica_name=actor_name,
                    )
                    r = _ReplicaState(actor_name, handle, uid)
                    r.ready_ref = handle.check_health.remote()
                    st.replicas.append(r)
                    active.append(r)
                # scale down (newest-first, reference removes most recent)
                while len(active) > st.target:
                    victim = active.pop()
                    st.replicas.remove(victim)
                    self._kill_replica(victim)
                    self._version += 1
                    self._change.notify_all()

    def _reap_draining(self, st: "_DeploymentState"):
        """Kill draining replicas once (a) the new version is serving and
        (b) they are idle (queue probe == 0) or the drain grace expired.
        Runs under the controller lock."""
        import ray_tpu

        draining = [r for r in st.replicas if r.draining]
        if not draining:
            return
        fresh_ready = sum(1 for r in st.replicas
                          if r.ready and not r.dead and not r.draining)
        if fresh_ready < st.target and st.target > 0:
            return  # old version still carries the traffic
        now = time.time()
        # moment traffic flipped to the new version: in-flight picks made
        # against the old routing need a beat to land before any kill
        if st.flip_at is None:
            st.flip_at = now
        if now - st.flip_at < 0.75:
            return
        for r in draining:
            idle = False
            if r.dead:
                idle = True
            elif now - (r.drain_since or now) > 10.0:
                idle = True  # grace expired: force
            else:
                if r.drain_probe is None:
                    r.drain_probe = r.handle.get_queue_len.remote()
                else:
                    done, _ = ray_tpu.wait([r.drain_probe], num_returns=1,
                                           timeout=0)
                    if done:
                        try:
                            idle = ray_tpu.get(r.drain_probe, timeout=1) == 0
                        except Exception:  # noqa: BLE001
                            idle = True  # already dead
                        r.drain_probe = None
            if idle:
                st.replicas.remove(r)
                self._kill_replica(r)
                self._version += 1
                self._change.notify_all()

    def _kill_replica(self, r: _ReplicaState):
        import ray_tpu

        try:
            ray_tpu.kill(r.handle)
        except Exception:  # noqa: BLE001
            pass

    def _autoscale(self):
        import ray_tpu

        # Snapshot replica lists AND the state generation under the lock:
        # deploy()/delete_deployment() run concurrently on other actor
        # threads and clear/replace st.replicas; the EMA/target update
        # below is skipped if the deployment changed underneath us.
        with self._lock:
            states = [(name, st, [r for r in st.replicas if r.ready
                                  and not r.dead and not r.draining],
                       st.version)
                      for name, st in self._deployments.items()]
        for name, st, ready, version in states:
            ac = st.spec.get("autoscaling_config")
            if not ac:
                continue
            if not ready:
                continue
            # probe in-flight counts (best effort, short timeout)
            total = 0
            probes = [(r, r.handle.get_queue_len.remote()) for r in ready]
            for r, ref in probes:
                try:
                    total += ray_tpu.get(ref, timeout=1.0)
                except Exception:  # noqa: BLE001
                    pass
            now = time.time()
            with self._lock:
                if (self._deployments.get(name) is not st
                        or st.version != version):
                    continue  # redeployed/deleted mid-probe: stale sample
                alpha = ac.get("smoothing_factor", 0.6)
                st.ongoing_ema = alpha * total + (1 - alpha) * st.ongoing_ema
                target_per = ac.get("target_ongoing_requests", 1.0)
                desired = math.ceil(st.ongoing_ema / max(target_per, 1e-9))
                desired = max(ac.get("min_replicas", 1),
                              min(ac.get("max_replicas", 1), desired))
                if desired > st.target:
                    st.under_since = None
                    if st.over_since is None:
                        st.over_since = now
                    if now - st.over_since >= ac.get("upscale_delay_s", 0.0):
                        st.target = desired
                        st.over_since = None
                elif desired < st.target:
                    st.over_since = None
                    if st.under_since is None:
                        st.under_since = now
                    if now - st.under_since >= ac.get(
                            "downscale_delay_s", 2.0):
                        st.target = desired
                        st.under_since = None
                else:
                    st.over_since = st.under_since = None
