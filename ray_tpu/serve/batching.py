"""``@serve.batch`` — transparent request batching inside a replica.

Reference analogue: `python/ray/serve/batching.py:337` (``@serve.batch``
wraps a method taking ``List[request]``; concurrent callers are grouped up
to ``max_batch_size`` or ``batch_wait_timeout_s``).  Implementation:
callers (replica actor threads — ``max_ongoing_requests`` gives the
concurrency) enqueue (request, future) pairs; one flusher thread per
wrapped function forms batches and distributes results.
"""

from __future__ import annotations

import functools
import queue
import threading
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable[[Any, List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.queue: "queue.Queue" = queue.Queue()
        self.batch_sizes: List[int] = []  # observability / tests
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="serve-batcher", daemon=True)
                self._thread.start()

    def _loop(self):
        import time

        while True:
            item = self.queue.get()  # block for the first element
            batch = [item]
            deadline = time.monotonic() + self.timeout
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self.queue.get(timeout=remaining))
                except queue.Empty:
                    break
            self.batch_sizes.append(len(batch))
            try:
                from ray_tpu.serve.telemetry import (
                    replica_identity,
                    serve_metrics,
                )

                dep = replica_identity()["deployment"]
                if dep:
                    serve_metrics()["batch"].observe(
                        float(len(batch)), tags={"deployment": dep})
            except Exception:  # noqa: BLE001 — telemetry never fails a batch
                pass
            owner = batch[0][0]
            requests = [req for _, req, _ in batch]
            try:
                results = self.fn(owner, requests) if owner is not None \
                    else self.fn(requests)
                if len(results) != len(requests):
                    raise ValueError(
                        f"batched function returned {len(results)} results "
                        f"for {len(requests)} requests")
                for (_, _, fut), res in zip(batch, results):
                    fut["result"] = res
                    fut["event"].set()
            except Exception as e:  # noqa: BLE001
                for _, _, fut in batch:
                    fut["error"] = e
                    fut["event"].set()

    def submit(self, owner, request, timeout: float = 60.0):
        self._ensure_thread()
        fut = {"event": threading.Event(), "result": None, "error": None}
        self.queue.put((owner, request, fut))
        if not fut["event"].wait(timeout):
            raise TimeoutError("batched call timed out")
        if fut["error"] is not None:
            raise fut["error"]
        return fut["result"]


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a method/function taking a LIST of requests; single-request
    calls are grouped transparently::

        @serve.deployment(max_ongoing_requests=32)
        class Model:
            @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.005)
            def __call__(self, inputs):      # inputs: List[request]
                return model_forward(inputs)  # List[response]
    """

    def wrap(fn):
        @functools.wraps(fn)
        def method_wrapper(self_or_req, *rest):
            b = _live_batcher(method_wrapper, fn, max_batch_size,
                              batch_wait_timeout_s)
            if rest:  # bound method: (self, request)
                return b.submit(self_or_req, rest[0])
            return b.submit(None, self_or_req)

        method_wrapper._is_serve_batch = True
        method_wrapper._batch_config = {
            "max_batch_size": max_batch_size,
            "batch_wait_timeout_s": batch_wait_timeout_s,
        }
        return method_wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


# The batcher holds threads/queues — never picklable, so it lives in a
# process-local registry rather than the (cloudpickled) closure.  Keyed by
# the wrapper's id: fresh per process after unpickling, shared across all
# instances of the deployment class in one replica.
_registry: dict = {}
_registry_lock = threading.Lock()


def _live_batcher(wrapper, fn, max_batch_size, batch_wait_timeout_s):
    key = id(wrapper)
    b = _registry.get(key)
    if b is None:
        with _registry_lock:
            b = _registry.setdefault(
                key, _Batcher(fn, max_batch_size, batch_wait_timeout_s))
    return b


def batch_sizes_of(wrapper) -> List[int]:
    """Observed batch sizes of a @batch-wrapped function IN THIS PROCESS
    (call from inside the replica, e.g. via a stats method)."""
    b = _registry.get(id(wrapper))
    return list(b.batch_sizes) if b else []
