"""Durable workflows: DAG execution with per-step checkpoints + resume.

Reference analogue: `python/ray/workflow/` (``workflow.run`` executes a
DAG of steps with storage-backed checkpoints; a crashed workflow resumes
from the last completed step; `workflow/api.py`).

TPU-first simplifications vs the reference: storage is a filesystem
directory (fsspec/cloud mounts work the same way), step identity is the
node's position in the deterministic topological order plus the function
name, and execution drives the existing task runtime — each step runs as
a normal task, its result is checkpointed before dependents run (the
"commit point"; reference `workflow/workflow_executor.py`).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.config import config
from ray_tpu.dag import DAGNode, FunctionNode, InputNode

config.define("workflow_dir", str, "",
              "Durable workflow storage root (default "
              "~/.ray_tpu/workflows).", live=True)

__all__ = ["run", "resume", "get_output", "get_status", "list_all",
           "delete", "init_storage"]

_storage_dir: Optional[str] = None


def init_storage(path: str):
    """Set the workflow storage root (reference: ``workflow.init``)."""
    global _storage_dir
    _storage_dir = path
    os.makedirs(path, exist_ok=True)


def _storage() -> str:
    global _storage_dir
    if _storage_dir is None:
        _storage_dir = (config.workflow_dir
                        or os.path.expanduser("~/.ray_tpu/workflows"))
        os.makedirs(_storage_dir, exist_ok=True)
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _write_meta(workflow_id: str, meta: dict):
    path = os.path.join(_wf_dir(workflow_id), "meta.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)


def _read_meta(workflow_id: str) -> Optional[dict]:
    try:
        with open(os.path.join(_wf_dir(workflow_id), "meta.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _step_ids(dag: DAGNode) -> Dict[int, str]:
    """Deterministic step ids: topo position + function name."""
    ids = {}
    for i, node in enumerate(dag.topo_order()):
        name = node.name if isinstance(node, FunctionNode) else "input"
        ids[id(node)] = f"{i:03d}_{name}"
    return ids


def _ckpt_path(workflow_id: str, step_id: str) -> str:
    return os.path.join(_wf_dir(workflow_id), step_id + ".pkl")


def _save_ckpt(workflow_id: str, step_id: str, value: Any):
    path = _ckpt_path(workflow_id, step_id)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(value, f, protocol=5)
    os.replace(tmp, path)  # atomic commit point


def _load_ckpt(workflow_id: str, step_id: str):
    with open(_ckpt_path(workflow_id, step_id), "rb") as f:
        return pickle.load(f)


def _execute(workflow_id: str, dag: DAGNode, dag_blob: bytes) -> Any:
    """Run the DAG step-by-step, checkpointing each result; completed
    steps (from a prior attempt) are skipped."""
    import ray_tpu

    ids = _step_ids(dag)
    os.makedirs(_wf_dir(workflow_id), exist_ok=True)
    with open(os.path.join(_wf_dir(workflow_id), "dag.pkl"), "wb") as f:
        f.write(dag_blob)
    _write_meta(workflow_id, {"workflow_id": workflow_id,
                              "status": "RUNNING",
                              "start_time": time.time()})
    results: Dict[int, Any] = {}
    try:
        for node in dag.topo_order():
            if not isinstance(node, FunctionNode):
                if isinstance(node, InputNode):
                    raise ValueError(
                        "workflow DAGs must be fully bound (no InputNode)")
                continue
            step_id = ids[id(node)]
            if os.path.exists(_ckpt_path(workflow_id, step_id)):
                results[id(node)] = _load_ckpt(workflow_id, step_id)
                continue
            args = [results[id(a)] if isinstance(a, DAGNode) else a
                    for a in node._args]
            kwargs = {k: results[id(v)] if isinstance(v, DAGNode) else v
                      for k, v in node._kwargs.items()}
            value = ray_tpu.get(node._fn.remote(*args, **kwargs))
            _save_ckpt(workflow_id, step_id, value)
            results[id(node)] = value
        out = results[id(dag.topo_order()[-1])]
        _save_ckpt(workflow_id, "__output__", out)
        _write_meta(workflow_id, {"workflow_id": workflow_id,
                                  "status": "SUCCESSFUL",
                                  "end_time": time.time()})
        return out
    except Exception as e:
        _write_meta(workflow_id, {"workflow_id": workflow_id,
                                  "status": "FAILED", "error": repr(e),
                                  "end_time": time.time()})
        raise


def run(dag: DAGNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a DAG durably; returns the final result (reference:
    ``workflow.run``).  Re-running a workflow_id whose steps partially
    completed skips the checkpointed steps."""
    import cloudpickle

    if workflow_id is None:
        workflow_id = f"wf-{int(time.time() * 1000):x}"
    meta = _read_meta(workflow_id)
    if meta and meta["status"] == "SUCCESSFUL":
        return _load_ckpt(workflow_id, "__output__")
    return _execute(workflow_id, dag, cloudpickle.dumps(dag))


def resume(workflow_id: str) -> Any:
    """Resume a crashed/failed workflow from its last checkpoint using the
    stored DAG (reference: ``workflow.resume``)."""
    import cloudpickle

    meta = _read_meta(workflow_id)
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if meta["status"] == "SUCCESSFUL":
        return _load_ckpt(workflow_id, "__output__")
    with open(os.path.join(_wf_dir(workflow_id), "dag.pkl"), "rb") as f:
        blob = f.read()
    dag = cloudpickle.loads(blob)
    return _execute(workflow_id, dag, blob)


def get_output(workflow_id: str) -> Any:
    meta = _read_meta(workflow_id)
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if meta["status"] != "SUCCESSFUL":
        raise RuntimeError(f"workflow {workflow_id!r} is {meta['status']}")
    return _load_ckpt(workflow_id, "__output__")


def get_status(workflow_id: str) -> str:
    meta = _read_meta(workflow_id)
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return meta["status"]


def list_all() -> List[Dict[str, Any]]:
    out = []
    root = _storage()
    for name in sorted(os.listdir(root)):
        meta = _read_meta(name)
        if meta:
            out.append(meta)
    return out


def delete(workflow_id: str):
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
