"""Trace analysis: span-tree reassembly, critical-path attribution,
cross-request aggregation, Perfetto/chrome://tracing export.

The raw material is the flat span records the tracing layer exports
(``util/tracing.py`` — one dict per finished span, cross-process parenting
via ``parent_id``).  Everything here is pure computation over those dicts
so the same code serves ``get_trace`` in the driver, the dashboard's
``/api/trace/<id>``, and the ``ray_tpu trace`` CLI.

Critical-path model: request hops are (mostly) sequential wall-clock
intervals — submit encode, raylet inbox, queue wait, dispatch, arg
resolution, execution, result push, seal, caller wakeup.  Attribution is a
sweep over the trace window assigning every instant to the LATEST-STARTED
span active at that instant (the most specific work going on: during
execution ``worker.exec`` out-ranks the enclosing ``task.run``, which
out-ranks the caller's ``task.get``); instants covered by no span are
``(untraced)``.  The attributed self-times sum exactly to the trace window,
so "where do the microseconds go" tables account for the whole request.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["build_tree", "critical_path", "aggregate", "to_chrome_trace"]

UNTRACED = "(untraced)"


def _hop_name(sp: dict) -> str:
    """Aggregation key: span name minus the per-request suffix
    (``raylet.queue sq.m`` -> ``raylet.queue``)."""
    return str(sp.get("name", "?")).split(" ", 1)[0]


def build_tree(spans: List[dict]) -> List[dict]:
    """Reassemble the cross-process span tree: each node is the span dict
    plus a ``children`` list (sorted by start time).  Spans whose parent
    never exported (e.g. an unsampled ancestor of an errored span) float
    up as roots rather than being dropped."""
    by_id: Dict[str, dict] = {}
    for sp in spans:
        node = dict(sp)
        node["children"] = []
        by_id[sp["span_id"]] = node
    roots: List[dict] = []
    for node in by_id.values():
        parent = by_id.get(node.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    for node in by_id.values():
        node["children"].sort(key=lambda n: n.get("start_us", 0))
    roots.sort(key=lambda n: n.get("start_us", 0))
    return roots


def critical_path(spans: List[dict]) -> Dict[str, Any]:
    """Latency waterfall + per-hop attribution for ONE trace.

    Returns ``{"total_us", "start_us", "rows", "by_hop"}``: ``rows`` is
    the waterfall (every span, start-ordered, with its attributed
    ``self_us``); ``by_hop`` sums attributed time per hop name (plus
    ``(untraced)`` for instants no span covered).  ``sum(by_hop.values())
    == total_us`` by construction."""
    spans = [sp for sp in spans if sp.get("duration_us") is not None]
    if not spans:
        return {"total_us": 0, "start_us": 0, "rows": [], "by_hop": {}}
    ivs = []  # (start, end, order-index, span)
    # sort: start ascending, then duration DESCENDING — among same-start
    # spans the shorter (more specific) one gets the higher order index
    # and wins the tie-break below
    for sp in sorted(spans, key=lambda s: (s["start_us"],
                                           -s.get("duration_us", 0))):
        s = sp["start_us"]
        ivs.append((s, s + max(0, sp.get("duration_us", 0)), len(ivs), sp))
    t0 = min(iv[0] for iv in ivs)
    t1 = max(iv[1] for iv in ivs)
    bounds = sorted({b for iv in ivs for b in iv[:2]})
    self_us = [0] * len(ivs)
    by_hop: Dict[str, int] = {}
    for lo, hi in zip(bounds, bounds[1:]):
        seg = hi - lo
        if seg <= 0:
            continue
        # latest-started active span wins the segment (ties: the later,
        # shorter entry — the more specific child)
        winner = None
        for iv in ivs:
            if iv[0] <= lo and iv[1] >= hi:
                if winner is None or (iv[0], iv[2]) >= (winner[0],
                                                        winner[2]):
                    winner = iv
        if winner is None:
            by_hop[UNTRACED] = by_hop.get(UNTRACED, 0) + seg
        else:
            self_us[winner[2]] += seg
            key = _hop_name(winner[3])
            by_hop[key] = by_hop.get(key, 0) + seg
    rows = []
    for start, end, idx, sp in ivs:
        rows.append({
            "name": sp.get("name"),
            "hop": _hop_name(sp),
            "span_id": sp.get("span_id"),
            "parent_id": sp.get("parent_id"),
            "offset_us": start - t0,
            "duration_us": end - start,
            "self_us": self_us[idx],
            "proc": sp.get("proc"),
            "node": sp.get("node"),
            "status": sp.get("status", "OK"),
        })
    return {"total_us": t1 - t0, "start_us": t0, "rows": rows,
            "by_hop": by_hop}


def _pct(sorted_vals: List[int], q: float) -> int:
    if not sorted_vals:
        return 0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def aggregate(spans: List[dict]) -> Dict[str, Any]:
    """The "where do the microseconds go" table: group spans by trace,
    run critical-path attribution per trace, then distribute — per hop:
    request count, p50/p95/total attributed self-time, and the hop's share
    of summed request latency.  This is the before/after yardstick for
    transport/cold-start work: run a fixed workload, diff the table."""
    by_trace: Dict[str, List[dict]] = {}
    for sp in spans:
        by_trace.setdefault(sp.get("trace_id", "?"), []).append(sp)
    per_hop: Dict[str, List[int]] = {}
    totals: List[int] = []
    errored = 0
    for tid, tspans in by_trace.items():
        cp = critical_path(tspans)
        totals.append(cp["total_us"])
        if any(sp.get("status") == "ERROR" for sp in tspans):
            errored += 1
        for hop_name, us in cp["by_hop"].items():
            per_hop.setdefault(hop_name, []).append(us)
    table = {}
    grand = sum(totals) or 1
    for hop_name, vals in per_hop.items():
        vals.sort()
        table[hop_name] = {
            "requests": len(vals),
            "p50_us": _pct(vals, 0.50),
            "p95_us": _pct(vals, 0.95),
            "total_us": sum(vals),
            "share": round(sum(vals) / grand, 4),
        }
    totals.sort()
    return {
        "requests": len(by_trace),
        "errored": errored,
        "e2e_p50_us": _pct(totals, 0.50),
        "e2e_p95_us": _pct(totals, 0.95),
        "by_hop": dict(sorted(table.items(),
                              key=lambda kv: -kv[1]["total_us"])),
    }


def to_chrome_trace(spans: List[dict]) -> Dict[str, Any]:
    """Perfetto / chrome://tracing JSON (object form with ``traceEvents``):
    one complete ('X') event per span, lanes keyed by producing process
    (proc label + node + pid), named via process_name metadata events."""
    events: List[dict] = []
    lanes: Dict[tuple, int] = {}
    for sp in spans:
        key = (sp.get("proc", "?"), sp.get("node", ""), sp.get("pid", 0))
        lane = lanes.get(key)
        if lane is None:
            lane = lanes[key] = len(lanes) + 1
            label = f"{key[0]} {key[1]}".strip() + f" (pid={key[2]})"
            events.append({"ph": "M", "name": "process_name", "pid": lane,
                           "tid": 0, "args": {"name": label}})
        args = dict(sp.get("attributes") or {})
        args.update({"trace_id": sp.get("trace_id"),
                     "span_id": sp.get("span_id"),
                     "parent_id": sp.get("parent_id"),
                     "status": sp.get("status", "OK")})
        if sp.get("error"):
            args["error"] = sp["error"]
        events.append({
            "ph": "X", "cat": "span",
            "name": sp.get("name", "?"),
            "pid": lane, "tid": lane,
            "ts": sp.get("start_us", 0),
            "dur": max(0, sp.get("duration_us", 0)),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
