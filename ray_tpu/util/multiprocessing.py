"""multiprocessing.Pool drop-in over actors.

Reference analogue: `python/ray/util/multiprocessing/pool.py` (``Pool`` —
the stdlib Pool API running each worker as an actor, so pools span the
cluster).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

__all__ = ["Pool"]


class _PoolWorker:
    def run(self, fn_blob: bytes, args: tuple, kwargs: dict):
        import cloudpickle

        fn = cloudpickle.loads(fn_blob)
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn_blob: bytes, items: List[tuple]):
        import cloudpickle

        fn = cloudpickle.loads(fn_blob)
        return [fn(*it) for it in items]


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None):
        import ray_tpu

        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        import ray_tpu

        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            self.get(timeout=5)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """``Pool(processes=4)`` — apply/map/starmap/imap + async variants."""

    def __init__(self, processes: int = 4,
                 ray_remote_args: Optional[dict] = None):
        import cloudpickle

        import ray_tpu

        self._cp = cloudpickle
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 1)
        worker_cls = ray_tpu.remote(**opts)(_PoolWorker)
        self._workers = [worker_cls.remote() for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        self._inflight: List[Any] = []

    def _next_worker(self):
        if self._closed:
            raise ValueError("Pool is closed")
        return self._workers[next(self._rr)]

    # --------------------------------------------------------------- apply

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        blob = self._cp.dumps(fn)
        ref = self._next_worker().run.remote(blob, tuple(args), kwds or {})
        self._inflight.append(ref)
        return AsyncResult([ref], single=True)

    # ----------------------------------------------------------------- map

    def _map_refs(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int], star: bool) -> List[Any]:
        items = [tuple(x) if star else (x,) for x in iterable]
        if not items:
            return []
        blob = self._cp.dumps(fn)
        if chunksize is None:
            chunksize = max(1, len(items) // (len(self._workers) * 4))
        refs = []
        for i in range(0, len(items), chunksize):
            refs.append(self._next_worker().run_batch.remote(
                blob, items[i:i + chunksize]))
        self._inflight.extend(refs)
        return refs

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> "AsyncResult":
        refs = self._map_refs(fn, iterable, chunksize, star=False)
        return _FlattenResult(refs)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        import ray_tpu

        refs = self._map_refs(fn, iterable, chunksize, star=True)
        return [x for chunk in ray_tpu.get(refs) for x in chunk]

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        import ray_tpu

        refs = self._map_refs(fn, iterable, chunksize, star=False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        import ray_tpu

        refs = self._map_refs(fn, iterable, chunksize, star=False)
        pending = list(refs)
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(done[0])

    # ------------------------------------------------------------ lifecycle

    def close(self):
        self._closed = True

    def terminate(self):
        import ray_tpu

        self._closed = True
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass

    def join(self):
        """Blocks until every submitted task finished (stdlib contract)."""
        import ray_tpu

        if not self._closed:
            raise ValueError("join() before close()")
        if self._inflight:
            ray_tpu.wait(self._inflight, num_returns=len(self._inflight))
            self._inflight = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


class _FlattenResult(AsyncResult):
    def __init__(self, refs):
        super().__init__(refs, single=False)

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return [x for chunk in chunks for x in chunk]
