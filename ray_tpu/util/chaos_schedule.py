"""Seeded, replayable compound-fault chaos schedules + a cluster
invariant bank.

The primitives already exist — ``Cluster.remove_node`` (SIGKILL),
SIGSTOP partitions, ``restart_gcs``, graceful drain, the chaos control
file (frame drops / slow exec), and the memory-usage OOM seam.  What
was missing is COMPOSITION: real outages are compound (a node dies
while the GCS is restarting; a partition heals into a drain), and
one-fault-per-test suites never walk those interleavings.  This module
turns the primitives into randomized timelines:

* ``build_schedule(seed, ...)`` — the planned timeline is a PURE
  function of its arguments.  One ``random.Random(seed)`` drives event
  spacing, fault kind, target slot, and per-fault parameters, so the
  same seed yields a byte-identical JSONL serialization, forever.
  Faults with a duration get their paired heal event generated at plan
  time.
* ``ChaosRunner`` — executes a timeline against a ``Cluster`` while
  pluggable workload generators (lineage-heavy task fan-out, a
  checkpointed actor writing side-effect marker files, replicated
  put/get, optionally a small Serve app) run underneath.  Every
  executed event is appended to a JSONL log with its wall-clock time
  and outcome; ``load_timeline`` strips the execution-only fields so a
  failing run's log replays the identical fault sequence.
* ``check_invariants(cluster, ...)`` — after the schedule heals, the
  bank asserts what must hold no matter which faults fired:
  exactly-once side effects, no lost acked work, conservation of
  accounting, convergence to green, metrics consistent with the fault
  log, and (via ``chaos.assert_clean_host``) no leaked processes.
* MTTR — each disruptive fault gets a watcher that records
  fault → cluster green → first successful probe call, the
  recovery-latency number the soak reports per fault kind.

Events target worker SLOTS (indices into the cluster's worker-node
list), not node ids: killed or drained nodes respawn into their slot
(``Cluster.replace_node``), so a schedule stays meaningful across the
very faults it injects.

Reference analogue: Ray's chaos tests compose ``NodeKillerActor`` with
long-running workloads (`python/ray/_private/test_utils.py:1416`,
`release/nightly_tests/chaos_test/`); the invariant bank plays the role
their progress checks + ``ray memory`` leak audits play, made explicit.
"""

from __future__ import annotations

import gc
import json
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock

__all__ = [
    "FAULT_KINDS", "MTTR_KINDS", "build_schedule", "timeline_to_jsonl",
    "write_timeline", "load_timeline", "Workload", "TaskFanoutWorkload",
    "ActorMarkerWorkload", "PutGetWorkload", "ServeWorkload",
    "ChaosRunner", "check_invariants", "check_converged",
    "check_acked_durable", "check_exactly_once", "check_accounting",
    "check_refs_drained", "check_metrics_consistent", "check_alerts_quiet",
    "render_report",
]

config.define("chaos_schedule_min_gap_s", float, 2.0,
              "Chaos schedules: minimum seconds between consecutive "
              "injected faults.  Spacing is drawn uniformly from "
              "[min_gap, max_gap] by the schedule's seeded RNG.")
config.define("chaos_schedule_max_gap_s", float, 6.0,
              "Chaos schedules: maximum seconds between consecutive "
              "injected faults.")
config.define("chaos_mttr_timeout_s", float, 90.0,
              "Chaos runner: how long an MTTR watcher waits for the "
              "cluster to return to green and serve a probe call after "
              "a fault before recording the recovery as timed out.")
config.define("chaos_soak_seed", int, 0,
              "Randomized soak (tests/test_chaos_schedule.py, slow tier): "
              "schedule seed.  CI varies it per run; a failure report "
              "names the seed so the exact timeline replays locally.")
config.define("chaos_soak_duration_s", float, 600.0,
              "Randomized soak: fault-injection window in seconds.")
config.define("chaos_quiesce_timeout_s", float, 60.0,
              "Invariant bank: how long convergence-to-green may take "
              "after the last fault heals before it counts as a "
              "violation (covers suspicion timeouts, reconstruction, "
              "and replication repair catching up).")

# ---------------------------------------------------------------------------
# schedule building + (de)serialization
# ---------------------------------------------------------------------------

#: Primary fault kinds a schedule can draw from.
FAULT_KINDS: Tuple[str, ...] = (
    "node_kill", "partition", "gcs_restart", "drain", "slow_exec", "oom")

#: Kinds that get an MTTR watcher (disruptive enough to dent the cluster).
MTTR_KINDS = frozenset(("node_kill", "partition", "gcs_restart", "drain",
                        "oom"))

#: Fault kind -> its paired heal event kind (generated at plan time).
_HEAL_OF = {"partition": "heal_partition", "slow_exec": "heal_slow_exec",
            "oom": "heal_oom"}

#: Keys a planned event carries.  Everything else on a logged event is
#: execution-only and stripped by ``load_timeline`` so replays are exact.
_PLAN_KEYS = ("idx", "t_s", "kind", "slot", "params")


def build_schedule(seed: int, duration_s: float,
                   faults: Sequence[str] = FAULT_KINDS,
                   n_slots: int = 2,
                   min_gap_s: Optional[float] = None,
                   max_gap_s: Optional[float] = None) -> List[dict]:
    """Deterministic fault timeline: a pure function of its arguments.

    One seeded RNG drives everything — spacing, kind, slot, params — in
    a fixed draw order, so equal inputs give byte-identical timelines.
    Faults with a duration (partition / slow_exec / oom) get their heal
    event appended at ``t + duration`` before the final time-sort.
    """
    for f in faults:
        if f not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {f!r} "
                             f"(choose from {FAULT_KINDS})")
    if n_slots < 1:
        raise ValueError("n_slots must be >= 1")
    lo = config.chaos_schedule_min_gap_s if min_gap_s is None else min_gap_s
    hi = config.chaos_schedule_max_gap_s if max_gap_s is None else max_gap_s
    rng = random.Random(seed)
    events: List[dict] = []
    seq = 0
    t = 0.0
    while True:
        t += rng.uniform(lo, max(lo, hi))
        if t >= duration_s:
            break
        kind = faults[rng.randrange(len(faults))]
        slot = rng.randrange(n_slots)
        params: Dict[str, Any] = {}
        if kind == "partition":
            params["duration_s"] = round(rng.uniform(1.5, 4.0), 3)
        elif kind == "slow_exec":
            params["delay_ms"] = (50, 150, 400)[rng.randrange(3)]
            params["p"] = round(rng.uniform(0.5, 1.0), 3)
            params["duration_s"] = round(rng.uniform(2.0, 6.0), 3)
        elif kind == "oom":
            params["usage"] = round(rng.uniform(0.95, 0.99), 3)
            params["duration_s"] = round(rng.uniform(1.0, 3.0), 3)
        elif kind == "drain":
            params["timeout_s"] = round(rng.uniform(3.0, 8.0), 3)
        ev = {"t_s": round(t, 3), "kind": kind, "slot": slot,
              "params": params, "_seq": seq}
        seq += 1
        events.append(ev)
        heal = _HEAL_OF.get(kind)
        if heal:
            events.append({"t_s": round(t + params["duration_s"], 3),
                           "kind": heal, "slot": slot, "params": {},
                           "_seq": seq})
            seq += 1
    # Stable order: by time, ties broken by creation order (so a heal
    # landing exactly on another event's time sorts deterministically).
    events.sort(key=lambda e: (e["t_s"], e["_seq"]))
    for i, ev in enumerate(events):
        del ev["_seq"]
        ev["idx"] = i
    return events


def timeline_to_jsonl(events: Sequence[dict]) -> str:
    """Canonical serialization — sorted keys, no whitespace — so equal
    timelines are byte-identical strings (the determinism contract)."""
    return "".join(
        json.dumps({k: ev[k] for k in _PLAN_KEYS}, sort_keys=True,
                   separators=(",", ":")) + "\n"
        for ev in events)


def write_timeline(events: Sequence[dict], path: str) -> None:
    with open(path, "w") as f:
        f.write(timeline_to_jsonl(events))


def load_timeline(path: str) -> List[dict]:
    """Load a timeline (planned OR executed log): execution-only fields
    are stripped so a failing run's log replays the identical faults."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not all(k in rec for k in _PLAN_KEYS):
                continue  # MTTR / summary records interleaved in a log
            events.append({k: rec[k] for k in _PLAN_KEYS})
    events.sort(key=lambda e: e["idx"])
    return events


# ---------------------------------------------------------------------------
# workload generators
# ---------------------------------------------------------------------------

def _lineage_leaf(k, n):
    import numpy as np

    return np.full(n, k, dtype=np.int64)


def _lineage_sum(arr):
    return int(arr.sum())


def _probe_fn(x):
    return 2 * x


class _MarkerActor:
    """Checkpointed counter whose every bump leaves a side-effect marker
    file — the exactly-once witness.  A tag written twice means some
    layer re-executed work it had already acknowledged."""

    def __init__(self, marker_dir):
        self.marker_dir = marker_dir
        self.n = 0

    def bump(self, tag):
        with open(os.path.join(self.marker_dir, tag), "a") as f:
            f.write("x")
        self.n += 1
        return self.n

    def __ray_save__(self):
        return self.n

    def __ray_restore__(self, state):
        self.n = state


class Workload:
    """Base workload: a driver-side submit loop with strict accounting.

    Subclasses implement ``_step(seq)`` (submit one unit, return
    ``(ref, expected)``) and optionally ``_check(value, expected)``.
    The base loop classifies every submission exactly once —
    succeeded / failed / cancelled / pending — so the invariant bank
    can reconcile totals after the storm."""

    name = "workload"
    interval_s = 0.08
    # Short enough that a fault-stalled get parks the unit in _inflight
    # (resolved at quiesce) instead of freezing the submit loop for the
    # rest of the storm.
    get_timeout_s = 5.0
    max_retained = 48

    def __init__(self, placement_resources: Optional[Dict[str, float]]
                 = None):
        # Pin the workload's tasks/actors onto the killable worker slots
        # (a custom resource the head node doesn't have) — otherwise the
        # scheduler happily parks everything on the never-faulted head
        # and the storm tests nothing.
        self.placement_resources = placement_resources
        self._lock = make_lock(f"chaos.wl.{self.name}")
        # guard: _lock — counters + retained acked (ref, expected) pairs
        self.counts = {"submitted": 0, "succeeded": 0, "failed": 0,
                       "cancelled": 0}
        self.acked: List[Tuple[Any, Any]] = []
        self._inflight: List[Tuple[Any, Any]] = []
        self.errors: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        self._setup()
        self._thread = threading.Thread(
            target=self._loop, name=f"chaos-wl-{self.name}", daemon=True)
        self._thread.start()

    def stop_submitting(self) -> None:
        self._stop.set()

    def quiesce(self, timeout_s: float = 60.0) -> None:
        """Join the submit loop, then resolve every still-pending ref —
        after this, ``pending`` must be 0 or accounting is broken."""
        import ray_tpu

        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
        deadline = time.monotonic() + timeout_s
        with self._lock:
            pending, self._inflight = self._inflight, []
        for ref, expected in pending:
            budget = max(1.0, deadline - time.monotonic())
            try:
                value = ray_tpu.get(ref, timeout=budget)
                self._classify_success(ref, value, expected)
            except ray_tpu.TaskCancelledError:
                self._count("cancelled")
            except Exception as e:  # noqa: BLE001 — any loss is 'failed'
                self._count("failed", note=type(e).__name__)

    def release(self) -> None:
        """Drop every retained ref (durability witnesses included) so the
        conservation check can watch the driver's ref table drain."""
        with self._lock:
            self.acked = []
            self._inflight = []

    # -- submit loop --------------------------------------------------
    def _setup(self) -> None:
        """Hook: build remote functions/actors (runs before the loop)."""

    def _step(self, seq: int):
        raise NotImplementedError

    def _check(self, value, expected) -> bool:
        return expected is None or value == expected

    def _loop(self) -> None:
        import ray_tpu

        seq = 0
        while not self._stop.is_set():
            try:
                ref, expected = self._step(seq)
            except Exception as e:  # noqa: BLE001 — submit-side failure
                self._count("submitted")
                self._count("failed", note=f"submit:{type(e).__name__}")
                self._stop.wait(self.interval_s * 4)
                seq += 1
                continue
            self._count("submitted")
            try:
                value = ray_tpu.get(ref, timeout=self.get_timeout_s)
                self._classify_success(ref, value, expected)
            except ray_tpu.GetTimeoutError:
                with self._lock:
                    self._inflight.append((ref, expected))
            except ray_tpu.TaskCancelledError:
                self._count("cancelled")
            except Exception as e:  # noqa: BLE001 — fault-induced loss
                self._count("failed", note=type(e).__name__)
            seq += 1
            self._stop.wait(self.interval_s)

    def _classify_success(self, ref, value, expected) -> None:
        if self._check(value, expected):
            with self._lock:
                self.counts["succeeded"] += 1
                self.acked.append((ref, expected))
                if len(self.acked) > self.max_retained:
                    self.acked.pop(0)
        else:
            self._count("failed", note="wrong value")

    def _count(self, key: str, note: Optional[str] = None) -> None:
        with self._lock:
            self.counts[key] += 1
            if note and len(self.errors) < 200:
                self.errors.append(note)

    # -- invariant feeds ----------------------------------------------
    def account(self) -> dict:
        with self._lock:
            out = dict(self.counts)
            out["pending"] = (out["submitted"] - out["succeeded"]
                              - out["failed"] - out["cancelled"]
                              - len(self._inflight))
            out["inflight"] = len(self._inflight)
            return out

    def recheck_acked(self, timeout_s: float = 45.0) -> List[str]:
        """No lost acked work: every ref the driver successfully got
        during the storm must STILL resolve to the same value (possibly
        via reconstruction / replication repair)."""
        import ray_tpu

        with self._lock:
            snapshot = list(self.acked)
        violations = []
        deadline = time.monotonic() + timeout_s
        for ref, expected in snapshot:
            budget = max(2.0, deadline - time.monotonic())
            try:
                value = ray_tpu.get(ref, timeout=budget)
            except Exception as e:  # noqa: BLE001 — acked data is gone
                violations.append(
                    f"{self.name}: acked ref {ref} lost "
                    f"({type(e).__name__}: {e})")
                continue
            if not self._check(value, expected):
                violations.append(
                    f"{self.name}: acked ref {ref} changed value "
                    f"(expected {expected!r})")
        return violations

    def marker_violations(self) -> List[str]:
        """Hook: exactly-once witnesses (only marker workloads have any)."""
        return []

    def tracked_oids(self) -> set:
        with self._lock:
            return {ref._id for ref, _ in self.acked
                    if hasattr(ref, "_id")}


class TaskFanoutWorkload(Workload):
    """Lineage-heavy fan-out: leaf produces a store-sized array, a child
    task reduces it.  Kills exercise lineage reconstruction; the
    retained leaf SUMS are the durability witnesses.  Every 13th
    submission is cancelled immediately — cancellation outcomes must
    still reconcile in the accounting check."""

    name = "fanout"
    payload_n = 32768  # 256 KiB of int64 — above the inline threshold

    def _setup(self) -> None:
        import ray_tpu

        opts = {"max_retries": 8}
        if self.placement_resources:
            opts["resources"] = dict(self.placement_resources)
        self._leaf = ray_tpu.remote(**opts)(_lineage_leaf)
        self._sum = ray_tpu.remote(**opts)(_lineage_sum)

    def _step(self, seq: int):
        import ray_tpu

        k = seq % 97 + 1
        leaf = self._leaf.remote(k, self.payload_n)
        ref = self._sum.remote(leaf)
        if seq % 13 == 5:
            ray_tpu.cancel(ref, recursive=True)
        return ref, k * self.payload_n


class ActorMarkerWorkload(Workload):
    """Checkpointed counter actor whose bumps write marker files — each
    call uses a FRESH tag (never reused on retry), so the filesystem is
    an exactly-once ledger: an acked tag must have exactly one marker
    byte, and ANY tag with two means double execution."""

    name = "marker"
    interval_s = 0.10
    get_timeout_s = 6.0

    def __init__(self, marker_dir: str,
                 placement_resources: Optional[Dict[str, float]] = None):
        super().__init__(placement_resources)
        self.marker_dir = marker_dir
        self.acked_tags: List[str] = []  # guard: _lock

    def _setup(self) -> None:
        import ray_tpu

        os.makedirs(self.marker_dir, exist_ok=True)
        opts = {"max_restarts": 50, "checkpoint_interval": 5}
        if self.placement_resources:
            opts["resources"] = dict(self.placement_resources)
        cls = ray_tpu.remote(**opts)(_MarkerActor)
        self._actor = cls.remote(self.marker_dir)

    def _step(self, seq: int):
        tag = f"{self.name}-{seq:06d}"
        ref = self._actor.bump.remote(tag)
        return ref, ("tag", tag)

    def _check(self, value, expected) -> bool:
        if isinstance(expected, tuple) and expected[0] == "tag":
            with self._lock:
                self.acked_tags.append(expected[1])
            return isinstance(value, int) and value >= 1
        return True

    def recheck_acked(self, timeout_s: float = 45.0) -> List[str]:
        # Actor-call returns are small ints delivered inline; the durable
        # witness here is the marker ledger, checked separately.
        return []

    def marker_violations(self) -> List[str]:
        with self._lock:
            acked = list(self.acked_tags)
        violations = []
        sizes: Dict[str, int] = {}
        try:
            names = os.listdir(self.marker_dir)
        except OSError:
            return [f"{self.name}: marker dir vanished"]
        for fname in names:
            if not fname.startswith(self.name + "-"):
                continue
            try:
                sizes[fname] = os.path.getsize(
                    os.path.join(self.marker_dir, fname))
            except OSError:
                sizes[fname] = -1
        for tag, size in sorted(sizes.items()):
            if size > 1:
                violations.append(
                    f"{self.name}: tag {tag} executed {size} times "
                    f"(exactly-once violated)")
        for tag in acked:
            if sizes.get(tag, 0) != 1:
                violations.append(
                    f"{self.name}: acked tag {tag} has "
                    f"{sizes.get(tag, 0)} marker bytes (want exactly 1)")
        return violations


class PutGetWorkload(Workload):
    """Replicated driver puts — exercises the replication/repair path;
    retained (ref, checksum) pairs feed the durability check."""

    name = "putget"
    interval_s = 0.12
    get_timeout_s = 5.0
    payload_n = 16384

    def _step(self, seq: int):
        import numpy as np
        import ray_tpu

        k = seq % 251
        arr = np.full(self.payload_n, k, dtype=np.int64)
        ref = ray_tpu.put(arr, _replicate=True)
        return ref, k * self.payload_n

    def _check(self, value, expected) -> bool:
        try:
            return int(value.sum()) == expected
        except AttributeError:
            return False


class ServeWorkload(Workload):
    """A one-replica Serve echo app under fire — admission, routing, and
    controller recovery all in the blast radius.  Shed/timeout responses
    count as ``failed`` and must still reconcile."""

    name = "serve"
    interval_s = 0.15
    get_timeout_s = 6.0

    def _setup(self) -> None:
        from ray_tpu import serve

        self._serve = serve
        serve.start()

        @serve.deployment(name="chaos_echo")
        def chaos_echo(req):
            return {"v": req["v"]}

        self._handle = serve.run(chaos_echo.bind(),
                                 route_prefix="/chaos_echo")

    def _step(self, seq: int):
        ref = self._handle.remote({"v": seq})
        return ref, {"v": seq}

    def recheck_acked(self, timeout_s: float = 45.0) -> List[str]:
        # Serve responses are request/reply, not durable objects.
        return []

    def release(self) -> None:
        super().release()
        try:
            self._serve.delete("chaos_echo")
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------

class ChaosRunner:
    """Execute a fault timeline against a ``Cluster`` while workloads
    run, logging each event (JSONL) and recording per-fault MTTR.

    The cluster must be built with ``chaos_control_file=`` (slow-exec
    steering) and, for ``oom`` faults, ``memory_usage_file=``; faults
    needing an absent seam are skipped and logged as such rather than
    silently dropped."""

    def __init__(self, cluster, events: Sequence[dict],
                 workloads: Sequence[Workload],
                 control_file: Optional[str] = None,
                 memory_file: Optional[str] = None,
                 log_path: Optional[str] = None,
                 mttr_timeout_s: Optional[float] = None,
                 time_scale: float = 1.0,
                 probe_resources: Optional[Dict[str, float]] = None):
        self.cluster = cluster
        self.events = [dict(ev) for ev in events]
        self.workloads = list(workloads)
        self.control_file = control_file
        self.memory_file = memory_file
        self.log_path = log_path
        self.time_scale = time_scale
        self.mttr_timeout_s = (config.chaos_mttr_timeout_s
                               if mttr_timeout_s is None else mttr_timeout_s)
        # Worker slots: every node except the head.  Slot index is the
        # schedule's addressing unit; ``replace_node`` keeps it stable.
        self.slots = [n for n in cluster.nodes
                      if n is not getattr(cluster, "head_node", None)]
        if not self.slots:
            raise ValueError("need at least one non-head worker node")
        self.executed: List[dict] = []
        self.mttr: Dict[str, List[float]] = {}   # guard: _lock
        self._lock = make_lock("chaos.runner")
        self._paused: set = set()                # guard: _lock
        self._watchers: List[threading.Thread] = []
        self._log_fh = open(log_path, "w") if log_path else None
        self.probe_resources = probe_resources
        self._probe = None

    # -- event log ----------------------------------------------------
    def _log(self, rec: dict) -> None:
        if self._log_fh is not None:
            self._log_fh.write(json.dumps(rec, sort_keys=True,
                                          separators=(",", ":")) + "\n")
            self._log_fh.flush()

    # -- control/memory file seams ------------------------------------
    def _write_ctrl(self, spec: dict) -> None:
        tmp = self.control_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(spec, f)
        os.replace(tmp, self.control_file)

    def _write_mem(self, usage: float) -> None:
        tmp = self.memory_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(usage))
        os.replace(tmp, self.memory_file)

    # -- fault dispatch -----------------------------------------------
    def _slot_node(self, slot: int):
        return self.slots[slot % len(self.slots)]

    def _inject(self, ev: dict) -> Tuple[bool, str]:
        kind, slot = ev["kind"], ev["slot"]
        params = ev.get("params") or {}
        node = self._slot_node(slot)
        if kind == "node_kill":
            new = self.cluster.replace_node(node)
            self.slots[slot % len(self.slots)] = new
            with self._lock:
                self._paused.discard(node)
            return True, f"killed {node.node_id[:8]} -> {new.node_id[:8]}"
        if kind == "partition":
            self.cluster.pause_node(node)
            with self._lock:
                self._paused.add(node)
            return True, f"paused {node.node_id[:8]}"
        if kind == "heal_partition":
            self.cluster.resume_node(node)
            with self._lock:
                self._paused.discard(node)
            return True, f"resumed {node.node_id[:8]}"
        if kind == "gcs_restart":
            if not getattr(self.cluster, "_gcs_persist", None):
                return False, "skipped: cluster has no gcs_persist_path"
            self.cluster.restart_gcs()
            return True, "gcs restarted"
        if kind == "drain":
            return self._inject_drain(node, slot, params)
        if kind == "slow_exec":
            if not self.control_file:
                return False, "skipped: no chaos control file"
            self._write_ctrl({"exec_delay": {
                "ms": params.get("delay_ms", 100),
                "p": params.get("p", 1.0), "names": ""}})
            return True, f"slow exec {params.get('delay_ms')}ms"
        if kind == "heal_slow_exec":
            if not self.control_file:
                return False, "skipped: no chaos control file"
            self._write_ctrl({})
            return True, "slow exec off"
        if kind == "oom":
            if not self.memory_file:
                return False, "skipped: no memory usage file"
            self._write_mem(params.get("usage", 0.97))
            return True, f"memory pressure {params.get('usage', 0.97)}"
        if kind == "heal_oom":
            if not self.memory_file:
                return False, "skipped: no memory usage file"
            self._write_mem(0.0)
            return True, "memory pressure off"
        return False, f"unknown fault kind {kind!r}"

    def _inject_drain(self, node, slot: int, params: dict):
        from ray_tpu.core.gcs import GcsClient

        timeout_s = params.get("timeout_s", 5.0)
        try:
            cli = GcsClient(self.cluster.address)
        except (ConnectionError, OSError) as e:
            return False, f"drain rpc failed: {e}"
        try:
            cli.drain_node(node.node_id, timeout_s=timeout_s)
        except Exception as e:  # noqa: BLE001 — e.g. node already dead
            cli.close()
            return False, f"drain rejected: {type(e).__name__}: {e}"

        def _await_drain():
            # joined-by: ChaosRunner.run (watchers list)
            deadline = time.monotonic() + timeout_s + 15.0
            state = "draining"
            while time.monotonic() < deadline:
                try:
                    state = cli.drain_status(node.node_id).get("state")
                except (ConnectionError, OSError):
                    break
                if state not in ("draining",):
                    break
                time.sleep(0.25)
            cli.close()
            # Drained node is spent — respawn its slot so the schedule
            # keeps its target count (a real autoscaler would do this).
            new = self.cluster.replace_node(node)
            with self._lock:
                self.slots[slot % len(self.slots)] = new
                self._paused.discard(node)

        t = threading.Thread(target=_await_drain,
                             name=f"chaos-drain-{node.node_id[:8]}",
                             daemon=True)
        t.start()
        self._watchers.append(t)
        return True, f"draining {node.node_id[:8]}"

    # -- recovery observation -----------------------------------------
    def _cluster_green(self) -> bool:
        from ray_tpu.core.gcs import GcsClient

        try:
            cli = GcsClient(self.cluster.address)
        except (ConnectionError, OSError):
            return False
        try:
            rows = [r for r in cli.nodes() if r.get("alive")]
            # Green means the CURRENT membership is alive — a killed
            # node's stale not-yet-declared-dead row must not count for
            # its replacement (that would zero out every MTTR reading).
            alive = {r["node_id"] for r in rows}
            want = {n.node_id for n in self.cluster.nodes}
            if not want <= alive:
                return False
            return not any(r.get("suspect") or r.get("draining")
                           for r in rows)
        except (ConnectionError, TimeoutError, OSError):
            return False
        finally:
            try:
                cli.close()
            except OSError:
                pass

    def _spawn_mttr_watcher(self, rec: dict) -> None:
        import ray_tpu

        if self._probe is None:
            opts = {"num_cpus": 0.01, "max_retries": 16}
            if self.probe_resources:
                opts["resources"] = dict(self.probe_resources)
            self._probe = ray_tpu.remote(**opts)(_probe_fn)
        t_fault = time.monotonic()
        kind, idx = rec["kind"], rec["idx"]

        def _watch():
            # joined-by: ChaosRunner.run (watchers list)
            deadline = t_fault + self.mttr_timeout_s
            while time.monotonic() < deadline:
                if self._cluster_green():
                    break
                time.sleep(0.25)
            else:
                rec["mttr_s"] = None
                self._log({"idx": idx, "kind": kind, "mttr_s": None})
                return
            while time.monotonic() < deadline:
                try:
                    if ray_tpu.get(self._probe.remote(idx), timeout=5) \
                            == 2 * idx:
                        mttr = round(time.monotonic() - t_fault, 3)
                        rec["mttr_s"] = mttr
                        with self._lock:
                            self.mttr.setdefault(kind, []).append(mttr)
                        self._log({"idx": idx, "kind": kind,
                                   "mttr_s": mttr})
                        return
                except Exception:  # noqa: BLE001 — still recovering
                    pass
                time.sleep(0.25)
            rec["mttr_s"] = None
            self._log({"idx": idx, "kind": kind, "mttr_s": None})

        t = threading.Thread(target=_watch, name=f"chaos-mttr-{idx}",
                             daemon=True)
        t.start()
        self._watchers.append(t)

    # -- main loop ----------------------------------------------------
    def heal_all(self) -> None:
        """Lift every still-standing fault (end of schedule or abort)."""
        with self._lock:
            paused = list(self._paused)
            self._paused.clear()
        for node in paused:
            try:
                self.cluster.resume_node(node)
            except OSError:
                pass
        if self.control_file:
            self._write_ctrl({})
        if self.memory_file:
            self._write_mem(0.0)

    def run(self, quiesce_timeout_s: Optional[float] = None) -> dict:
        """Start workloads, walk the timeline, heal, quiesce, and run the
        invariant bank.  Returns the report (see ``check_invariants``),
        augmented with the executed log and per-kind MTTR stats."""
        if self.control_file:
            self._write_ctrl({})
        if self.memory_file:
            self._write_mem(0.0)
        for w in self.workloads:
            w.start()
        t0 = time.monotonic()
        try:
            for ev in self.events:
                target = t0 + ev["t_s"] * self.time_scale
                delay = target - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                try:
                    ok, detail = self._inject(ev)
                except Exception as e:  # noqa: BLE001 — log, keep going
                    ok, detail = False, f"{type(e).__name__}: {e}"
                rec = dict(ev)
                rec["t_wall"] = round(time.monotonic() - t0, 3)
                rec["ok"] = ok
                rec["detail"] = detail
                self.executed.append(rec)
                self._log(rec)
                if ok and ev["kind"] in MTTR_KINDS:
                    self._spawn_mttr_watcher(rec)
        finally:
            self.heal_all()
        for w in self.workloads:
            w.stop_submitting()
        for w in self.workloads:
            w.quiesce()
        join_deadline = time.monotonic() + self.mttr_timeout_s + 10.0
        for t in self._watchers:
            t.join(max(0.5, join_deadline - time.monotonic()))
        report = check_invariants(
            self.cluster, workloads=self.workloads,
            fault_log=self.executed,
            quiesce_timeout_s=quiesce_timeout_s)
        report["mttr_s"] = self.mttr_summary()
        report["events_executed"] = len(self.executed)
        self._log({"report": {k: report[k] for k in
                              ("ok", "violations", "mttr_s")}})
        if self._log_fh is not None:
            self._log_fh.close()
            self._log_fh = None
        return report

    def mttr_summary(self) -> Dict[str, dict]:
        with self._lock:
            snapshot = {k: list(v) for k, v in self.mttr.items()}
        out = {}
        timeouts = {}
        for rec in self.executed:
            if rec.get("ok") and rec["kind"] in MTTR_KINDS \
                    and rec.get("mttr_s", "absent") is None:
                timeouts[rec["kind"]] = timeouts.get(rec["kind"], 0) + 1
        for kind, samples in sorted(snapshot.items()):
            out[kind] = {"n": len(samples),
                         "mean_s": round(sum(samples) / len(samples), 3),
                         "max_s": round(max(samples), 3),
                         "timeouts": timeouts.get(kind, 0)}
        for kind, n in timeouts.items():
            out.setdefault(kind, {"n": 0, "mean_s": None, "max_s": None,
                                  "timeouts": n})
        return out


# ---------------------------------------------------------------------------
# the invariant bank
# ---------------------------------------------------------------------------

def _checker(name: str, fn: Callable[[], Tuple[bool, str]]) -> dict:
    try:
        ok, detail = fn()
    except Exception as e:  # noqa: BLE001 — a crashed checker is a failure
        ok, detail = False, f"checker crashed: {type(e).__name__}: {e}"
    return {"name": name, "ok": bool(ok), "detail": detail}


def check_converged(cluster, timeout_s: Optional[float] = None) -> dict:
    """Convergence to green: all expected nodes ALIVE, none stuck
    SUSPECT or DRAINING, within the quiesce window."""
    from ray_tpu.core.gcs import GcsClient

    budget = (config.chaos_quiesce_timeout_s
              if timeout_s is None else timeout_s)

    def _run():
        deadline = time.monotonic() + budget
        last = "no gcs contact"
        while time.monotonic() < deadline:
            try:
                cli = GcsClient(cluster.address)
            except (ConnectionError, OSError) as e:
                last = f"gcs unreachable: {e}"
                time.sleep(0.25)
                continue
            try:
                rows = [r for r in cli.nodes() if r.get("alive")]
                bad = [r["node_id"][:8] for r in rows
                       if r.get("suspect") or r.get("draining")]
                alive = {r["node_id"] for r in rows}
                want = {n.node_id for n in cluster.nodes}
                missing = [nid[:8] for nid in want - alive]
                if not missing and not bad:
                    return True, (f"{len(rows)} alive, 0 suspect, "
                                  f"0 draining")
                last = (f"{len(alive)} alive, missing={missing}, "
                        f"stragglers={bad}")
            except (ConnectionError, TimeoutError, OSError) as e:
                last = f"gcs query failed: {e}"
            finally:
                try:
                    cli.close()
                except OSError:
                    pass
            time.sleep(0.25)
        return False, f"not green after {budget}s: {last}"

    return _checker("converged_green", _run)


def check_acked_durable(workloads: Sequence[Workload],
                        timeout_s: float = 45.0) -> dict:
    """No lost acked work: every get() that resolved during the storm
    still resolves (reconstruction / replication count as resolving)."""
    def _run():
        violations = []
        total = 0
        for w in workloads:
            total += len(w.acked)
            violations += w.recheck_acked(timeout_s)
        if violations:
            return False, "; ".join(violations[:10])
        return True, f"{total} retained acked refs all re-resolved"

    return _checker("acked_durable", _run)


def check_exactly_once(workloads: Sequence[Workload]) -> dict:
    """Exactly-once side effects: no marker tag written twice; every
    acked tag written exactly once."""
    def _run():
        violations = []
        for w in workloads:
            violations += w.marker_violations()
        if violations:
            return False, "; ".join(violations[:10])
        return True, "marker ledger clean"

    return _checker("exactly_once", _run)


def check_accounting(workloads: Sequence[Workload]) -> dict:
    """Conservation of accounting: every submission is classified
    exactly once — succeeded + failed + cancelled == submitted, nothing
    pending after quiesce."""
    def _run():
        problems = []
        detail = []
        for w in workloads:
            a = w.account()
            detail.append(f"{w.name}:{a}")
            if a["pending"] != 0 or a["inflight"] != 0:
                problems.append(
                    f"{w.name}: {a['pending']} unclassified + "
                    f"{a['inflight']} inflight of {a['submitted']}")
            if a["submitted"] == 0:
                problems.append(f"{w.name}: submitted nothing "
                                f"(workload never ran)")
        if problems:
            return False, "; ".join(problems)
        return True, " ".join(detail)

    return _checker("accounting", _run)


def check_refs_drained(workloads: Sequence[Workload],
                       grace_s: float = 10.0) -> dict:
    """Ref-count conservation: once the workloads drop their retained
    refs, the driver's ref table must forget those objects (a surviving
    entry is a leaked reference)."""
    def _run():
        tracked = set()
        for w in workloads:
            tracked |= w.tracked_oids()
            w.release()
        gc.collect()
        from ray_tpu.core import worker as worker_mod

        deadline = time.monotonic() + grace_s
        leaked = tracked
        while True:
            with worker_mod._ref_lock:
                leaked = tracked & set(worker_mod._ref_counts)
            if not leaked:
                return True, f"{len(tracked)} refs drained"
            if time.monotonic() >= deadline:
                break
            time.sleep(0.2)
        return False, (f"{len(leaked)} of {len(tracked)} released refs "
                       f"still in the driver ref table")

    return _checker("refs_drained", _run)


def check_metrics_consistent(fault_log: Sequence[dict]) -> dict:
    """Recovery metrics must be explainable by the fault log: lineage
    reconstruction with no destructive fault in the log means the
    runtime lost data on its own."""
    def _run():
        destructive = {"node_kill", "partition", "oom", "gcs_restart"}
        injected = {ev["kind"] for ev in fault_log if ev.get("ok", True)}
        failed_drain = any(
            ev["kind"] == "drain" and ev.get("ok", True)
            and "drained" not in str(ev.get("detail", ""))
            for ev in fault_log)
        from ray_tpu.util import state as state_api
        from ray_tpu.util.metrics_query import sum_deltas

        res = state_api.query_metrics(
            "ray_tpu_internal_reconstruction_attempts_total", op="range")
        if res is None:
            return True, "no metrics table (local mode)"
        attempts = sum_deltas(res.get("points", ()))
        if attempts > 0 and not (injected & destructive) \
                and not failed_drain:
            return False, (f"{attempts:.0f} reconstruction attempts but "
                           f"the fault log has no destructive fault "
                           f"(injected: {sorted(injected)})")
        return True, (f"{attempts:.0f} reconstruction attempts, "
                      f"faults: {sorted(injected)}")

    return _checker("metrics_consistent", _run)


#: Alert rules a fault kind legitimately trips (windows are 60–300 s, so
#: they can still be firing right after quiesce).  Info-severity export
#: overflow alerts are always excusable — observability pressure, not a
#: correctness signal.
ALLOWED_ALERTS_BY_FAULT: Dict[str, frozenset] = {
    "node_kill": frozenset(("replication_repair_pressure",
                            "false_suspect_rate")),
    "partition": frozenset(("false_suspect_rate", "fenced_frame_spike",
                            "replication_repair_pressure")),
    "gcs_restart": frozenset(("fenced_frame_spike", "false_suspect_rate")),
    "oom": frozenset(("replication_repair_pressure",)),
    "drain": frozenset(("replication_repair_pressure",)),
    "slow_exec": frozenset(("serve_p99_latency", "serve_shed_burn")),
}
_ALWAYS_EXCUSED_ALERTS = frozenset((
    "task_event_drops", "trace_span_drops", "profile_sample_drops",
    "metric_point_drops"))


def check_alerts_quiet(fault_log: Sequence[dict]) -> dict:
    """No firing alerts after quiesce — except those attributable to the
    faults we injected (their rule windows outlive the storm)."""
    def _run():
        from ray_tpu.util import state as state_api

        res = state_api.list_alerts(state="firing")
        if res is None:
            return True, "no alert engine (local mode)"
        firing = res.get("firing", ())
        allowed = set(_ALWAYS_EXCUSED_ALERTS)
        for ev in fault_log:
            if ev.get("ok", True):
                allowed |= ALLOWED_ALERTS_BY_FAULT.get(ev["kind"],
                                                       frozenset())
        bad = [a for a in firing if a.get("rule") not in allowed]
        if bad:
            names = sorted({a.get("rule") or "?" for a in bad})
            return False, f"unexplained firing alerts: {names}"
        excused = sorted({a.get("rule") or "?" for a in firing})
        return True, (f"{len(firing)} firing, all excused by fault log "
                      f"({excused})" if firing else "no firing alerts")

    return _checker("alerts_quiet", _run)


def check_invariants(cluster, workloads: Sequence[Workload] = (),
                     fault_log: Sequence[dict] = (),
                     quiesce_timeout_s: Optional[float] = None) -> dict:
    """Run the full bank.  Order matters: convergence first (the other
    checks assume a green cluster can serve gets), durability before
    ``refs_drained`` (which releases the witnesses)."""
    checks = [
        check_converged(cluster, quiesce_timeout_s),
        check_acked_durable(workloads),
        check_exactly_once(workloads),
        check_accounting(workloads),
        check_metrics_consistent(fault_log),
        check_alerts_quiet(fault_log),
        check_refs_drained(workloads),
    ]
    violations = [c["name"] for c in checks if not c["ok"]]
    return {"ok": not violations, "checks": checks,
            "violations": violations}


def render_report(report: dict) -> str:
    """Human-readable invariant + MTTR report (the CLI's output)."""
    lines = ["chaos invariant report",
             "======================"]
    for c in report["checks"]:
        mark = "PASS" if c["ok"] else "FAIL"
        lines.append(f"  [{mark}] {c['name']}: {c['detail']}")
    mttr = report.get("mttr_s") or {}
    if mttr:
        lines.append("")
        lines.append("  MTTR by fault kind")
        lines.append(f"  {'kind':<14}{'n':>4}{'mean_s':>10}"
                     f"{'max_s':>10}{'timeouts':>10}")
        for kind, s in sorted(mttr.items()):
            mean = "-" if s["mean_s"] is None else f"{s['mean_s']:.2f}"
            mx = "-" if s["max_s"] is None else f"{s['max_s']:.2f}"
            lines.append(f"  {kind:<14}{s['n']:>4}{mean:>10}{mx:>10}"
                         f"{s['timeouts']:>10}")
    lines.append("")
    lines.append("  verdict: " + ("OK" if report["ok"] else
                                  "VIOLATIONS: " +
                                  ", ".join(report["violations"])))
    return "\n".join(lines)
