"""Chaos / fault-injection helpers for tests.

Reference analogue: `python/ray/_private/test_utils.py:1400`
(NodeKillerActor / ResourceKillerActor, ``kill_raylet :1741``) and
`python/ray/tests/test_chaos.py`.  Two tools:

  * ``NodeKiller`` — periodically SIGKILLs a random worker NODE of the
    fake in-machine cluster (never the head), optionally respawning a
    replacement, so retries, actor failover, and lineage reconstruction
    are exercised under real process death.

  * ``NetworkChaos`` — deterministic, seedable network-fault injection on
    the runtime's own sockets: frame drop / delay / blackhole on raylet
    PEER connections and on the zero-copy DATA channels.  Env-gated via
    ``RAY_TPU_CHAOS_*`` so spawned raylet processes pick it up, or
    configured programmatically with :func:`configure_net` for the
    in-process raylet.  The send/serve hot paths call :func:`net_fault`,
    which is a no-op attribute check when chaos is disabled.

    Env knobs (all probabilities in [0,1]):
      RAY_TPU_CHAOS_NET_SEED         deterministic RNG seed (default 0)
      RAY_TPU_CHAOS_NET_DROP_P       drop a frame/response entirely
      RAY_TPU_CHAOS_NET_DELAY_P      delay a frame before sending
      RAY_TPU_CHAOS_NET_DELAY_MS     the injected delay, milliseconds
      RAY_TPU_CHAOS_NET_BLACKHOLE_P  partition the connection: every
                                     later frame on it vanishes silently
      RAY_TPU_CHAOS_NET_CHANNELS     csv of channels to afflict
                                     ("peer", "data"; default "data" —
                                     peer control frames have no
                                     per-frame retry, so dropping them
                                     is an explicit opt-in)

    A fault decision sequence is fully determined by (seed, sequence of
    ``net_fault`` calls), so a single-threaded workload replays exactly;
    multi-threaded callers still get a reproducible fault MIX.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock

config.define("chaos_net_seed", int, 0,
              "Network-chaos deterministic RNG seed.", live=True)
config.define("chaos_net_drop_p", float, 0.0,
              "Network chaos: probability a frame/response is dropped "
              "entirely.", live=True)
config.define("chaos_net_delay_p", float, 0.0,
              "Network chaos: probability a frame is delayed before "
              "sending.", live=True)
config.define("chaos_net_delay_ms", float, 0.0,
              "Network chaos: injected delay, milliseconds.", live=True)
config.define("chaos_net_blackhole_p", float, 0.0,
              "Network chaos: probability a connection is partitioned — "
              "every later frame on it vanishes silently.", live=True)
config.define("chaos_net_channels", str, "data",
              "Network chaos: csv of channels to afflict ('peer', "
              "'data').  Defaults to data only — peer control frames "
              "have no per-frame retry, so dropping them is an explicit "
              "opt-in.", live=True)

__all__ = ["NodeKiller", "NetworkChaos", "net_fault", "configure_net",
           "net"]


class NodeKiller:
    """Background thread killing random worker nodes of a Cluster at an
    interval; optionally respawns a replacement so capacity survives."""

    def __init__(self, cluster, kill_interval_s: float = 1.0,
                 respawn: bool = True, seed: Optional[int] = None,
                 max_kills: int = 1_000_000):
        self.cluster = cluster
        self.kill_interval_s = kill_interval_s
        self.respawn = respawn
        self.max_kills = max_kills
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="node-killer",
                                        daemon=True)

    def start(self) -> "NodeKiller":
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.kill_interval_s):
            if len(self.killed) >= self.max_kills:
                return
            head = getattr(self.cluster, "head_node", None)
            victims = [n for n in self.cluster.nodes
                       if n is not head and n.alive()]
            if not victims:
                continue
            node = self._rng.choice(victims)
            resources = dict(node.resources)
            store_mb = 64
            self.cluster.remove_node(node)  # SIGKILL
            self.killed.append(node.node_id)
            if self.respawn:
                cpus = resources.pop("CPU", 1)
                tpus = resources.pop("TPU", 0)
                try:
                    self.cluster.add_node(
                        num_cpus=cpus, num_tpus=tpus,
                        resources=resources or None,
                        object_store_mb=store_mb)
                except Exception:  # noqa: BLE001 — cluster shutting down
                    return

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


# ---------------------------------------------------------------------------
# Network fault injection


class NetworkChaos:
    """Seedable fault decisions for the runtime's sockets.  One instance
    per process; decisions are drawn from a private ``random.Random`` so a
    fixed seed gives a reproducible fault sequence."""

    __slots__ = ("enabled", "seed", "drop_p", "delay_p", "delay_s",
                 "blackhole_p", "channels", "_rng", "_lock", "faults")

    def __init__(self, drop_p: float = 0.0, delay_p: float = 0.0,
                 delay_ms: float = 0.0, blackhole_p: float = 0.0,
                 seed: int = 0, channels: Optional[List[str]] = None):
        self.drop_p = max(0.0, drop_p)
        self.delay_p = max(0.0, delay_p)
        self.delay_s = max(0.0, delay_ms) / 1e3
        self.blackhole_p = max(0.0, blackhole_p)
        # Default to the DATA channel only: the pull manager's watchdogs
        # retry/rotate lost data frames, but peer control frames (xtask,
        # xdone, pull) are fire-and-forget over TCP — the runtime has no
        # per-frame ack, so dropping them simulates a failure mode the
        # real transport cannot produce and recovery is not defined for.
        # Afflicting "peer" is an explicit opt-in (delay is safe there;
        # drop/blackhole model a partition the control plane does not
        # currently heal).
        self.channels = frozenset(channels or ("data",))
        self.seed = seed
        self.enabled = (self.drop_p > 0 or self.delay_p > 0
                        or self.blackhole_p > 0)
        self._rng = random.Random(seed)  # guard: _lock
        self._lock = make_lock("chaos.net")
        # injected-fault counts by kind, for test assertions
        self.faults = {"drop": 0, "delay": 0, "blackhole": 0}

    @classmethod
    def from_env(cls) -> "NetworkChaos":
        channels = [c.strip()
                    for c in config.chaos_net_channels.split(",")
                    if c.strip()]
        return cls(drop_p=config.chaos_net_drop_p,
                   delay_p=config.chaos_net_delay_p,
                   delay_ms=config.chaos_net_delay_ms,
                   blackhole_p=config.chaos_net_blackhole_p,
                   seed=config.chaos_net_seed, channels=channels)

    def decide(self, channel: str) -> Optional[str]:
        """Draw a fault for one frame on ``channel``:
        None | "drop" | "delay" | "blackhole"."""
        if not self.enabled or channel not in self.channels:
            return None
        with self._lock:
            r = self._rng.random()
            if r < self.blackhole_p:
                self.faults["blackhole"] += 1
                return "blackhole"
            r -= self.blackhole_p
            if r < self.drop_p:
                self.faults["drop"] += 1
                return "drop"
            r -= self.drop_p
            if r < self.delay_p:
                self.faults["delay"] += 1
                return "delay"
        return None


_net: Optional[NetworkChaos] = None


def net() -> NetworkChaos:
    """The process's NetworkChaos instance (env-configured on first use)."""
    global _net
    if _net is None:
        _net = NetworkChaos.from_env()
    return _net


def configure_net(**kwargs) -> NetworkChaos:
    """Programmatic (re)configuration — for the in-process raylet in
    tests.  Pass the NetworkChaos constructor kwargs; omit all to reset
    from the environment."""
    global _net
    _net = NetworkChaos(**kwargs) if kwargs else NetworkChaos.from_env()
    return _net


def net_fault(channel: str) -> Optional[str]:
    """Hot-path hook: a fault decision for one outbound frame, or None.
    Costs one attribute check when chaos is disabled."""
    n = _net
    if n is None:
        n = net()
    if not n.enabled:
        return None
    fault = n.decide(channel)
    if fault == "delay":
        time.sleep(n.delay_s)
        return None  # the frame still goes out, late
    return fault
