"""Chaos / fault-injection helpers for tests.

Reference analogue: `python/ray/_private/test_utils.py:1400`
(NodeKillerActor / ResourceKillerActor, ``kill_raylet :1741``) and
`python/ray/tests/test_chaos.py`.  Two tools:

  * ``NodeKiller`` — periodically SIGKILLs a random worker NODE of the
    fake in-machine cluster (never the head), optionally respawning a
    replacement, so retries, actor failover, and lineage reconstruction
    are exercised under real process death.

  * ``NetworkChaos`` — deterministic, seedable network-fault injection on
    the runtime's own sockets: frame drop / delay / blackhole on raylet
    PEER connections and on the zero-copy DATA channels.  Env-gated via
    ``RAY_TPU_CHAOS_*`` so spawned raylet processes pick it up, or
    configured programmatically with :func:`configure_net` for the
    in-process raylet.  The send/serve hot paths call :func:`net_fault`,
    which is a no-op attribute check when chaos is disabled.

    Env knobs (all probabilities in [0,1]):
      RAY_TPU_CHAOS_NET_SEED         deterministic RNG seed (default 0)
      RAY_TPU_CHAOS_NET_DROP_P       drop a frame/response entirely
      RAY_TPU_CHAOS_NET_DELAY_P      delay a frame before sending
      RAY_TPU_CHAOS_NET_DELAY_MS     the injected delay, milliseconds
      RAY_TPU_CHAOS_NET_BLACKHOLE_P  partition the connection: every
                                     later frame on it vanishes silently
      RAY_TPU_CHAOS_NET_CHANNELS     csv of channels to afflict
                                     ("peer", "data"; default "data" —
                                     peer control frames have no
                                     per-frame retry, so dropping them
                                     is an explicit opt-in)

    A fault decision sequence is fully determined by (seed, sequence of
    ``net_fault`` calls), so a single-threaded workload replays exactly;
    multi-threaded callers still get a reproducible fault MIX.

    * **Partitions** — deterministic blackholing between THIS process and
      a named peer (or every peer, ``"*"``), in one or both directions:
      ``net().partition(peer, direction="both"|"out"|"in")`` then
      ``net().heal(peer)`` restores the link.  ``direction`` is relative
      to this process: ``out`` swallows frames it sends toward the peer,
      ``in`` swallows frames arriving from it (the data server drops the
      peer's requests).  Unlike the probabilistic ``blackhole`` fault —
      which latches the connection dead at the call site — partition
      drops are decided per frame, so ``heal()`` genuinely restores
      traffic on the same sockets (partition → resurrect scenarios).
      Partitions apply to every channel unless ``channels=`` narrows
      them.  Spawned processes are steered through a control FILE
      (``RAY_TPU_CHAOS_NET_PARTITION_FILE``): JSON
      ``{"partitions": {"<peer-or-*>": "<direction>"}}``, re-read at
      most every 50 ms, so a test driver can partition and heal a live
      raylet process by rewriting the file.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional

from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock

config.define("chaos_net_seed", int, 0,
              "Network-chaos deterministic RNG seed.", live=True)
config.define("chaos_net_drop_p", float, 0.0,
              "Network chaos: probability a frame/response is dropped "
              "entirely.", live=True)
config.define("chaos_net_delay_p", float, 0.0,
              "Network chaos: probability a frame is delayed before "
              "sending.", live=True)
config.define("chaos_net_delay_ms", float, 0.0,
              "Network chaos: injected delay, milliseconds.", live=True)
config.define("chaos_net_blackhole_p", float, 0.0,
              "Network chaos: probability a connection is partitioned — "
              "every later frame on it vanishes silently.", live=True)
config.define("chaos_net_channels", str, "data",
              "Network chaos: csv of channels to afflict ('peer', "
              "'data').  Defaults to data only — peer control frames "
              "have no per-frame retry, so dropping them is an explicit "
              "opt-in.", live=True)
config.define("chaos_exec_delay_ms", float, 0.0,
              "Execution chaos: inject this delay (milliseconds) before a "
              "matching task executes on a worker — makes an executor "
              "pathologically slow without sleeps in user code "
              "(deadline/shedding tests).  0 disables.", live=True)
config.define("chaos_exec_delay_names", str, "",
              "Execution chaos: csv of substrings matched against task "
              "names (e.g. 'Replica.handle_request'); empty = every "
              "task.", live=True)
config.define("chaos_exec_delay_p", float, 1.0,
              "Execution chaos: probability a matching call is delayed, "
              "drawn from a deterministic RNG seeded by "
              "RAY_TPU_CHAOS_NET_SEED (replayable delay sequences).",
              live=True)
config.define("chaos_net_partition_file", str, "",
              "Network chaos: path of a JSON control file "
              "({'partitions': {'<peer-node-id-or-*>': "
              "'both'|'out'|'in'}}) steering deterministic per-peer "
              "partitions in THIS process.  Re-read at most every 50 ms, "
              "so a test driver partitions and heals a spawned raylet by "
              "rewriting the file.  Empty disables.", live=True)

__all__ = ["NodeKiller", "NetworkChaos", "net_fault", "configure_net",
           "net", "exec_delay", "snapshot_host", "assert_clean_host",
           "HostLeakError"]


class NodeKiller:
    """Background thread killing random worker nodes of a Cluster at an
    interval; optionally respawns a replacement so capacity survives."""

    def __init__(self, cluster, kill_interval_s: float = 1.0,
                 respawn: bool = True, seed: Optional[int] = None,
                 max_kills: int = 1_000_000):
        self.cluster = cluster
        self.kill_interval_s = kill_interval_s
        self.respawn = respawn
        self.max_kills = max_kills
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="node-killer",
                                        daemon=True)

    def start(self) -> "NodeKiller":
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.kill_interval_s):
            if len(self.killed) >= self.max_kills:
                return
            head = getattr(self.cluster, "head_node", None)
            victims = [n for n in self.cluster.nodes
                       if n is not head and n.alive()]
            if not victims:
                continue
            node = self._rng.choice(victims)
            resources = dict(node.resources)
            store_mb = 64
            self.cluster.remove_node(node)  # SIGKILL
            self.killed.append(node.node_id)
            if self.respawn:
                cpus = resources.pop("CPU", 1)
                tpus = resources.pop("TPU", 0)
                try:
                    self.cluster.add_node(
                        num_cpus=cpus, num_tpus=tpus,
                        resources=resources or None,
                        object_store_mb=store_mb)
                except Exception:  # noqa: BLE001 — cluster shutting down
                    return

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


# ---------------------------------------------------------------------------
# Network fault injection


class NetworkChaos:
    """Seedable fault decisions for the runtime's sockets.  One instance
    per process; decisions are drawn from a private ``random.Random`` so a
    fixed seed gives a reproducible fault sequence."""

    __slots__ = ("enabled", "seed", "drop_p", "delay_p", "delay_s",
                 "blackhole_p", "channels", "_rng", "_lock", "faults",
                 "partitions", "partition_file", "_pfile_at",
                 "exec_override")

    def __init__(self, drop_p: float = 0.0, delay_p: float = 0.0,
                 delay_ms: float = 0.0, blackhole_p: float = 0.0,
                 seed: int = 0, channels: Optional[List[str]] = None,
                 partition_file: Optional[str] = None):
        self.drop_p = max(0.0, drop_p)
        self.delay_p = max(0.0, delay_p)
        self.delay_s = max(0.0, delay_ms) / 1e3
        self.blackhole_p = max(0.0, blackhole_p)
        # Default to the DATA channel only: the pull manager's watchdogs
        # retry/rotate lost data frames, but peer control frames (xtask,
        # xdone, pull) are fire-and-forget over TCP — the runtime has no
        # per-frame ack, so dropping them simulates a failure mode the
        # real transport cannot produce and recovery is not defined for.
        # Afflicting "peer" is an explicit opt-in (delay is safe there;
        # drop/blackhole model a partition the control plane does not
        # currently heal).
        self.channels = frozenset(channels or ("data",))
        self.seed = seed
        self.enabled = (self.drop_p > 0 or self.delay_p > 0
                        or self.blackhole_p > 0)
        self._rng = random.Random(seed)  # guard: _lock
        self._lock = make_lock("chaos.net")
        # injected-fault counts by kind, for test assertions
        self.faults = {"drop": 0, "delay": 0, "blackhole": 0,
                       "partition": 0}
        # peer node_id (or "*") -> {"direction", "channels"} — see
        # partition()/heal().  Partition drops are deterministic (no RNG
        # draw) so heal() restores traffic exactly.
        self.partitions: dict = {}  # guard: _lock
        self.partition_file = partition_file or None
        self._pfile_at = 0.0  # last control-file refresh  # guard: _lock
        # control-file slow-exec steering: {"ms", "p", "names"} or None.
        # Lets a test driver toggle RAY_TPU_CHAOS_EXEC_DELAY_* semantics in
        # SPAWNED processes (their env is frozen at spawn) by rewriting
        # the control file — exec_delay() consults this before config.
        self.exec_override: Optional[dict] = None  # guard: _lock

    @classmethod
    def from_env(cls) -> "NetworkChaos":
        channels = [c.strip()
                    for c in config.chaos_net_channels.split(",")
                    if c.strip()]
        return cls(drop_p=config.chaos_net_drop_p,
                   delay_p=config.chaos_net_delay_p,
                   delay_ms=config.chaos_net_delay_ms,
                   blackhole_p=config.chaos_net_blackhole_p,
                   seed=config.chaos_net_seed, channels=channels,
                   partition_file=config.chaos_net_partition_file or None)

    # ---- deterministic per-peer partitions -------------------------------

    def partition(self, peer: str = "*", direction: str = "both",
                  channels: Optional[List[str]] = None):
        """Blackhole traffic between this process and ``peer`` (a node id,
        or ``"*"`` for every peer).  ``direction`` is relative to THIS
        process: ``out`` (frames we send toward the peer), ``in`` (frames
        arriving from it), or ``both``.  Applies to every chaos-hooked
        channel unless ``channels`` narrows it."""
        if direction not in ("both", "out", "in"):
            raise ValueError(f"direction {direction!r} not in both/out/in")
        with self._lock:
            self.partitions[peer] = {
                "direction": direction,
                "channels": frozenset(channels) if channels else None,
            }

    def heal(self, peer: Optional[str] = None):
        """Restore the link to ``peer`` (or every partitioned peer)."""
        with self._lock:
            if peer is None:
                self.partitions.clear()
            else:
                self.partitions.pop(peer, None)

    def _refresh_partitions_locked(self):  # requires: _lock
        """Re-read the control file (test driver -> spawned process
        steering), at most every 50 ms."""
        now = time.monotonic()
        if now - self._pfile_at < 0.05:
            return
        self._pfile_at = now
        import json
        try:
            with open(self.partition_file) as f:
                spec = json.load(f)
        except (OSError, ValueError):
            return  # missing/garbled file: keep the last applied state
        entries = spec.get("partitions") or {}
        self.partitions = {
            peer: {"direction": direction
                   if direction in ("both", "out", "in") else "both",
                   "channels": None}
            for peer, direction in entries.items()
        }
        ov = spec.get("exec_delay")
        if isinstance(ov, dict) and float(ov.get("ms", 0) or 0) > 0:
            self.exec_override = {
                "ms": float(ov["ms"]),
                "p": float(ov.get("p", 1.0)),
                "names": str(ov.get("names", "")),
            }
        else:
            self.exec_override = None

    def exec_override_state(self) -> Optional[dict]:
        """Current control-file slow-exec override ({'ms','p','names'}) or
        None.  Refreshes the control file on the same 50 ms cadence as the
        partition state."""
        if not self.partition_file:
            return None
        with self._lock:
            self._refresh_partitions_locked()
            return self.exec_override

    def _partitioned_locked(self, channel: str, peer: Optional[str],  # requires: _lock
                            direction: str) -> bool:
        for key in (peer, "*"):
            if key is None:
                continue
            ent = self.partitions.get(key)
            if ent is None:
                continue
            if ent["channels"] is not None and channel not in ent["channels"]:
                continue
            if ent["direction"] in ("both", direction):
                return True
        return False

    def decide(self, channel: str, peer: Optional[str] = None,
               direction: str = "out") -> Optional[str]:
        """Draw a fault for one frame on ``channel``:
        None | "drop" | "delay" | "blackhole".  Partition drops are
        checked first and are deterministic (no RNG draw — replay
        sequences are unchanged by partition windows)."""
        if self.partition_file \
                or self.partitions:  # unguarded-ok: empty-check fast path; re-checked under _lock below
            with self._lock:
                if self.partition_file:
                    self._refresh_partitions_locked()
                if self._partitioned_locked(channel, peer, direction):
                    self.faults["partition"] += 1
                    return "drop"
        if not self.enabled or channel not in self.channels:
            return None
        with self._lock:
            r = self._rng.random()
            if r < self.blackhole_p:
                self.faults["blackhole"] += 1
                return "blackhole"
            r -= self.blackhole_p
            if r < self.drop_p:
                self.faults["drop"] += 1
                return "drop"
            r -= self.drop_p
            if r < self.delay_p:
                self.faults["delay"] += 1
                return "delay"
        return None


_net: Optional[NetworkChaos] = None


def net() -> NetworkChaos:
    """The process's NetworkChaos instance (env-configured on first use)."""
    global _net
    if _net is None:
        _net = NetworkChaos.from_env()
    return _net


def configure_net(**kwargs) -> NetworkChaos:
    """Programmatic (re)configuration — for the in-process raylet in
    tests.  Pass the NetworkChaos constructor kwargs; omit all to reset
    from the environment."""
    global _net
    _net = NetworkChaos(**kwargs) if kwargs else NetworkChaos.from_env()
    return _net


_exec_rng: Optional[random.Random] = None
_exec_rng_lock = make_lock("chaos.exec_delay")


def exec_delay(task_name: str) -> float:
    """Seeded slow-executor injection, called by the worker between
    arg-pull and exec: sleep ``RAY_TPU_CHAOS_EXEC_DELAY_MS`` when the task
    name matches ``RAY_TPU_CHAOS_EXEC_DELAY_NAMES`` (csv substrings; empty
    matches all) with probability ``RAY_TPU_CHAOS_EXEC_DELAY_P`` (drawn
    from an RNG seeded by ``RAY_TPU_CHAOS_NET_SEED``, so delay sequences
    replay).  Returns the injected delay in seconds (0 = none).  Live
    flags: the check costs two env reads per execution when disabled.

    When a chaos control file is configured
    (``RAY_TPU_CHAOS_NET_PARTITION_FILE``), an ``exec_delay`` entry in it
    overrides the env knobs — the file is re-read live, so a schedule
    driver can open and close slow-executor windows in already-spawned
    workers (their env is frozen at spawn)."""
    global _exec_rng
    ms = config.chaos_exec_delay_ms
    names_csv = config.chaos_exec_delay_names
    p = config.chaos_exec_delay_p
    ov = None
    n = _net
    if n is not None and n.partition_file:
        ov = n.exec_override_state()
    elif n is None and config.chaos_net_partition_file:
        ov = net().exec_override_state()
    if ov is not None:
        ms, p, names_csv = ov["ms"], ov["p"], ov["names"]
    if ms <= 0:
        return 0.0
    names = [nm.strip() for nm in names_csv.split(",") if nm.strip()]
    if names and not any(nm in task_name for nm in names):
        return 0.0
    if p < 1.0:
        with _exec_rng_lock:
            if _exec_rng is None:
                _exec_rng = random.Random(config.chaos_net_seed)
            if _exec_rng.random() >= p:
                return 0.0
    delay = ms / 1e3
    time.sleep(delay)
    return delay


def net_fault(channel: str, peer: Optional[str] = None,
              direction: str = "out") -> Optional[str]:
    """Hot-path hook: a fault decision for one frame, or None.  Costs a
    few attribute checks when chaos is disabled.  ``peer``/``direction``
    feed the deterministic partition check (see NetworkChaos.partition);
    probabilistic faults ignore them."""
    n = _net
    if n is None:
        n = net()
    if not n.enabled and not n.partition_file \
            and not n.partitions:  # unguarded-ok: empty-check fast path; decide() re-checks under _lock
        return None
    fault = n.decide(channel, peer=peer, direction=direction)
    if fault == "delay":
        time.sleep(n.delay_s)
        return None  # the frame still goes out, late
    return fault


# ---------------------------------------------------------------------------
# Clean-host audit: no orphan runtime processes / shm segments / socket fds
# after a cluster is torn down.  Factored out of the manual verify recipe so
# cluster-spinning tests fail loudly on leaks instead of leaving them for a
# human `pgrep` at review time.

# argv module names of every spawnable runtime process.  Matched as EXACT
# argv elements (``/proc/<pid>/cmdline`` is NUL-separated), never as
# substrings — test harnesses and editors routinely hold these strings
# inside one long quoted argument and must not count as runtime orphans.
_RUNTIME_MODULES = frozenset((
    "ray_tpu.core.worker_main",
    "ray_tpu.core.raylet_main",
    "ray_tpu.core.gcs_main",
))


class HostLeakError(AssertionError):
    """A runtime process, shm segment, or socket fd outlived its cluster."""


def _runtime_pids() -> Dict[int, str]:
    """pid -> module name for every live runtime process on this host."""
    out: Dict[int, str] = {}
    me = os.getpid()
    try:
        pids = [int(d) for d in os.listdir("/proc") if d.isdigit()]
    except OSError:  # pragma: no cover — non-Linux
        return out
    for pid in pids:
        if pid == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().split(b"\x00")
        except OSError:
            continue  # raced an exit
        for arg in argv:
            name = arg.decode("utf-8", "replace")
            if name in _RUNTIME_MODULES:
                out[pid] = name
                break
    return out


def _shm_segments() -> List[str]:
    """Live ray_tpu object-store segments under /dev/shm."""
    try:
        return sorted(n for n in os.listdir("/dev/shm")
                      if n.startswith("rt_store"))
    except OSError:  # pragma: no cover — no /dev/shm
        return []


def _socket_fd_count() -> int:
    """Open socket fds of THIS process (driver-side leak detector)."""
    try:
        fds = os.listdir("/proc/self/fd")
    except OSError:  # pragma: no cover — non-Linux
        return 0
    n = 0
    for fd in fds:
        try:
            if os.readlink(f"/proc/self/fd/{fd}").startswith("socket:"):
                n += 1
        except OSError:
            continue
    return n


def snapshot_host() -> dict:
    """Baseline for :func:`assert_clean_host`: take it BEFORE starting a
    cluster so pre-existing processes/segments (other sessions, the test
    harness itself) are excluded from the leak check."""
    return {"pids": _runtime_pids(), "shm": set(_shm_segments()),
            "socket_fds": _socket_fd_count()}


def assert_clean_host(baseline: Optional[dict] = None,
                      grace_s: float = 15.0,
                      check_sockets: bool = False):
    """Assert no runtime process, object-store shm segment, or (opt-in)
    driver socket fd outlived the cluster(s) torn down since ``baseline``.

    Teardown is asynchronous (workers die on socket EOF, raylets reap on
    SIGTERM), so the check POLLS up to ``grace_s`` before declaring a
    leak.  Raises :class:`HostLeakError` listing the survivors.

    ``check_sockets`` compares this process's open socket-fd count to the
    baseline — off by default because long-lived test fixtures (shared
    runtimes, metric pollers) legitimately hold sockets across calls.
    """
    base_pids = set((baseline or {}).get("pids", {}))
    base_shm = set((baseline or {}).get("shm", ()))
    deadline = time.monotonic() + grace_s
    while True:
        pids = {p: m for p, m in _runtime_pids().items()
                if p not in base_pids}
        shm = [s for s in _shm_segments() if s not in base_shm]
        leaks = []
        if pids:
            leaks.append("orphan processes: " + ", ".join(
                f"pid {p} ({m})" for p, m in sorted(pids.items())))
        if shm:
            leaks.append("leaked shm segments: " + ", ".join(shm))
        if check_sockets and baseline is not None:
            extra = _socket_fd_count() - baseline.get("socket_fds", 0)
            if extra > 0:
                leaks.append(f"{extra} leaked socket fd(s) in this process")
        if not leaks:
            return
        if time.monotonic() >= deadline:
            raise HostLeakError(
                "host not clean after cluster teardown — " +
                "; ".join(leaks))
        time.sleep(0.25)
