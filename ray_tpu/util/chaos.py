"""Chaos / fault-injection helpers for tests.

Reference analogue: `python/ray/_private/test_utils.py:1400`
(NodeKillerActor / ResourceKillerActor, ``kill_raylet :1741``) and
`python/ray/tests/test_chaos.py`.  Works against the fake in-machine
cluster (`ray_tpu/cluster_utils.py`): periodically SIGKILLs a random
worker NODE (never the head) while a workload runs, so retries, actor
failover, and lineage reconstruction are exercised under real process
death.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

__all__ = ["NodeKiller"]


class NodeKiller:
    """Background thread killing random worker nodes of a Cluster at an
    interval; optionally respawns a replacement so capacity survives."""

    def __init__(self, cluster, kill_interval_s: float = 1.0,
                 respawn: bool = True, seed: Optional[int] = None,
                 max_kills: int = 1_000_000):
        self.cluster = cluster
        self.kill_interval_s = kill_interval_s
        self.respawn = respawn
        self.max_kills = max_kills
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="node-killer",
                                        daemon=True)

    def start(self) -> "NodeKiller":
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.kill_interval_s):
            if len(self.killed) >= self.max_kills:
                return
            head = getattr(self.cluster, "head_node", None)
            victims = [n for n in self.cluster.nodes
                       if n is not head and n.alive()]
            if not victims:
                continue
            node = self._rng.choice(victims)
            resources = dict(node.resources)
            store_mb = 64
            self.cluster.remove_node(node)  # SIGKILL
            self.killed.append(node.node_id)
            if self.respawn:
                cpus = resources.pop("CPU", 1)
                tpus = resources.pop("TPU", 0)
                try:
                    self.cluster.add_node(
                        num_cpus=cpus, num_tpus=tpus,
                        resources=resources or None,
                        object_store_mb=store_mb)
                except Exception:  # noqa: BLE001 — cluster shutting down
                    return

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
