"""Declarative alert rules over the metrics time-series table.

A rule is a plain JSON-able dict; the GCS evaluates the active rule set
on its metrics flush cadence against its own ``_metric_points`` table and
appends typed alert records (firing / resolved transitions) into a
bounded alert table (``gcs.list_alerts``, ``ray_tpu alerts``,
``/api/alerts``).  Evaluation itself is a pure function over a point
query callback, so the firing/resolve semantics are testable without a
cluster.

Two rule kinds:

* ``threshold`` — aggregate one series over a trailing window (``rate``,
  ``sum``, ``last``, ``max``, ``p50``/``p90``/``p99``) and compare against
  a bound::

      {"name": "fenced_frame_spike", "kind": "threshold",
       "metric": "ray_tpu_internal_fenced_frames_total",
       "agg": "rate", "window_s": 60, "op": ">", "threshold": 1.0,
       "severity": "warn", "summary": "..."}

* ``burn_rate`` — multi-window SLO burn (Google SRE workbook shape): the
  bad/total event ratio must exceed ``factor`` times the error budget
  (``1 - objective``) in BOTH a short and a long trailing window.  The
  long window gates on sustained damage, the short window makes the alert
  resolve promptly once the condition clears::

      {"name": "serve_shed_burn", "kind": "burn_rate",
       "bad": "ray_tpu_internal_serve_shed_total",
       "total": "ray_tpu_internal_serve_requests_total",
       "objective": 0.99, "short_s": 15, "long_s": 120, "factor": 10,
       "severity": "critical", "summary": "..."}

Ratios are computed from delta sums over each window, so a partially
filled window is exact (both numerator and denominator cover the same
span) — no warm-up distortion.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.util import metrics_query as mq

__all__ = ["evaluate_rules", "default_rules", "load_rules", "eval_threshold",
           "eval_burn_rate"]

# query callback: (metric_name, tags, since) -> list of point dicts
QueryFn = Callable[[str, Optional[Dict[str, str]], Optional[float]],
                   List[dict]]

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


def default_rules() -> List[dict]:
    """Built-in rules for the invariants earlier PRs established."""
    return [
        {"name": "false_suspect_rate", "kind": "threshold",
         "metric": "ray_tpu_internal_false_suspects_total",
         "agg": "rate", "window_s": 300.0, "op": ">", "threshold": 0.02,
         "severity": "warn",
         "summary": "failure detector is suspecting healthy nodes "
                    "(probes keep rescuing them) — check net health or "
                    "raise gcs_node_suspect_s"},
        {"name": "fenced_frame_spike", "kind": "threshold",
         "metric": "ray_tpu_internal_fenced_frames_total",
         "agg": "rate", "window_s": 60.0, "op": ">", "threshold": 1.0,
         "severity": "warn",
         "summary": "stale-incarnation frames are being fenced at a "
                    "sustained rate — a zombie raylet or partitioned "
                    "node is still talking"},
        {"name": "replication_repair_pressure", "kind": "threshold",
         "metric": "ray_tpu_internal_replication_repairs_total",
         "agg": "rate", "window_s": 120.0, "op": ">", "threshold": 1.0,
         "severity": "warn",
         "summary": "replication repair is running continuously — "
                    "object copies are being lost faster than steady "
                    "state"},
        {"name": "serve_shed_burn", "kind": "burn_rate",
         "bad": "ray_tpu_internal_serve_shed_total",
         "total": "ray_tpu_internal_serve_requests_total",
         "objective": 0.99, "short_s": 15.0, "long_s": 120.0,
         "factor": 10.0, "severity": "critical",
         "summary": "Serve is shedding requests fast enough to burn the "
                    "99% admission SLO 10x faster than budget — scale "
                    "out replicas or shed upstream"},
        {"name": "serve_p99_latency", "kind": "threshold",
         "metric": "ray_tpu_internal_serve_request_latency_s",
         "agg": "p99", "window_s": 60.0, "op": ">", "threshold": 1.0,
         "severity": "warn",
         "summary": "Serve p99 request latency is above the 1s default "
                    "objective over the last minute"},
        {"name": "task_event_drops", "kind": "threshold",
         "metric": "ray_tpu_internal_task_events_dropped_total",
         "agg": "rate", "window_s": 60.0, "op": ">", "threshold": 0.0,
         "severity": "info",
         "summary": "task-event export buffer is overflowing — state "
                    "API history has holes (raise "
                    "task_event_export_buffer)"},
        {"name": "trace_span_drops", "kind": "threshold",
         "metric": "ray_tpu_internal_trace_spans_dropped_total",
         "agg": "rate", "window_s": 60.0, "op": ">", "threshold": 0.0,
         "severity": "info",
         "summary": "trace spans are being dropped before export — "
                    "lower the sample rate or raise trace_buffer_size"},
        {"name": "profile_sample_drops", "kind": "threshold",
         "metric": "ray_tpu_internal_profile_samples_dropped_total",
         "agg": "rate", "window_s": 60.0, "op": ">", "threshold": 0.0,
         "severity": "info",
         "summary": "profile samples are being dropped before export"},
        {"name": "metric_point_drops", "kind": "threshold",
         "metric": "ray_tpu_internal_metric_points_dropped_total",
         "agg": "rate", "window_s": 60.0, "op": ">", "threshold": 0.0,
         "severity": "info",
         "summary": "metric time-series points are being dropped before "
                    "export — raise metrics_history_ring"},
    ]


def load_rules() -> List[dict]:
    """The active rule set: defaults (unless disabled) overridden/extended
    by the RAY_TPU_ALERTS_RULES JSON list, keyed by rule name.  Malformed
    JSON or non-list payloads are ignored rather than killing the health
    monitor."""
    rules = {r["name"]: r for r in default_rules()} \
        if config.alerts_default_rules else {}
    raw = config.alerts_rules
    if raw:
        try:
            extra = json.loads(raw)
        except ValueError:
            extra = None
        if isinstance(extra, list):
            for r in extra:
                if isinstance(r, dict) and r.get("name"):
                    rules[r["name"]] = r
    return list(rules.values())


def eval_threshold(rule: dict, query: QueryFn, now: float
                   ) -> Tuple[bool, Optional[float]]:
    """Evaluate one threshold rule.  Returns ``(firing, value)``;
    ``value`` is None when the window holds no data (never firing —
    absence of telemetry is the drop-counter rules' job, not a threshold
    breach)."""
    window = float(rule.get("window_s", 60.0))
    pts = query(rule["metric"], rule.get("tags"), now - window)
    pts = [p for p in pts if p["ts"] <= now]
    agg = rule.get("agg", "rate")
    value: Optional[float]
    if agg == "rate":
        value = mq.rate(pts, window, now=now) if pts else None
    elif agg == "sum":
        value = mq.sum_deltas(pts) if pts else None
    elif agg == "last":
        value = mq.last_value(pts)
    elif agg == "max":
        vals = [p["value"] for p in pts
                if not isinstance(p["value"], list)]
        value = max(vals) if vals else None
    elif agg in ("p50", "p90", "p99"):
        q = {"p50": 0.5, "p90": 0.9, "p99": 0.99}[agg]
        value = mq.quantile_over_window(pts, q, window, now=now)
    else:
        raise ValueError(f"unknown agg {agg!r} in rule {rule['name']!r}")
    if value is None:
        return False, None
    op = _OPS[rule.get("op", ">")]
    return op(value, float(rule["threshold"])), value


def eval_burn_rate(rule: dict, query: QueryFn, now: float
                   ) -> Tuple[bool, Optional[float]]:
    """Evaluate one multi-window burn-rate rule.  Returns ``(firing,
    value)`` where ``value`` is the binding (smaller) window's burn
    multiple — how many times faster than budget the SLO is burning."""
    budget = 1.0 - float(rule.get("objective", 0.99))
    if budget <= 0:
        raise ValueError(f"objective must be < 1 in rule {rule['name']!r}")
    factor = float(rule.get("factor", 10.0))
    tags = rule.get("tags")
    burns = []
    for window in (float(rule.get("short_s", 15.0)),
                   float(rule.get("long_s", 120.0))):
        bad_pts = [p for p in query(rule["bad"], tags, now - window)
                   if p["ts"] <= now]
        tot_pts = [p for p in query(rule["total"], tags, now - window)
                   if p["ts"] <= now]
        bad = mq.sum_deltas(bad_pts)
        total = mq.sum_deltas(tot_pts)
        ratio = (bad / total) if total > 0 else 0.0
        burns.append(ratio / budget)
    value = min(burns)
    return value > factor, value


def evaluate_rules(rules: List[dict], query: QueryFn, now: float,
                   active: Dict[str, dict]) -> List[dict]:
    """One evaluation pass.  ``active`` (rule name -> firing record) is
    mutated in place to track alert state across passes; the return value
    is the list of NEW transition records to append to the alert log —
    one on firing, one on resolve, nothing while a state persists.  A
    rule that errors (bad metric name, malformed spec) is skipped: one
    broken rule must not silence the rest."""
    records: List[dict] = []
    for rule in rules:
        try:
            if rule.get("kind") == "burn_rate":
                firing, value = eval_burn_rate(rule, query, now)
                threshold = float(rule.get("factor", 10.0))
            else:
                firing, value = eval_threshold(rule, query, now)
                threshold = float(rule["threshold"])
        except Exception:  # noqa: BLE001 — skip broken rule, keep rest
            continue
        name = rule["name"]
        cur = active.get(name)
        if firing:
            if cur is None:
                rec = {"rule": name, "state": "firing",
                       "severity": rule.get("severity", "warn"),
                       "kind": rule.get("kind", "threshold"),
                       "value": value, "threshold": threshold,
                       "since": now, "ts": now,
                       "summary": rule.get("summary", "")}
                active[name] = rec
                records.append(dict(rec))
            else:
                # still firing: refresh the live view, no new log record
                cur["value"] = value
                cur["ts"] = now
        elif cur is not None:
            active.pop(name)
            rec = dict(cur)
            rec.update({"state": "resolved", "value": value,
                        "ts": now})
            records.append(rec)
    return records
