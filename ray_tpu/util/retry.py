"""Unified retry/backoff policy for transient-failure paths.

One jittered-exponential-backoff policy (reference: the exponential
backoff helpers in `python/ray/_private/utils.py` and the gRPC channel
retry knobs in `ray_config_def.h`) shared by every ad-hoc retry loop in
the runtime — GCS client reconnect, data-channel dials, pull-manager
directory re-lookups — instead of each site hardcoding its own sleep
constant.  Defaults come from the config registry
(``RAY_TPU_RETRY_BACKOFF_*``); a policy can be seeded so chaos tests get
reproducible delay sequences.
"""

from __future__ import annotations

import random
from typing import Optional

from ray_tpu.core.config import config

config.define("retry_backoff_base_s", float, 0.2,
              "Unified retry policy: first-attempt backoff delay.  Used by "
              "the GCS reconnect loop, data-channel dials, and pull-manager "
              "directory re-lookups.")
config.define("retry_backoff_max_s", float, 5.0,
              "Unified retry policy: backoff delay ceiling.")
config.define("retry_backoff_multiplier", float, 2.0,
              "Unified retry policy: per-attempt delay multiplier.")
config.define("retry_backoff_jitter", float, 0.2,
              "Unified retry policy: +/- jitter fraction applied to each "
              "delay (0 disables; keeps retry storms from synchronizing).")


class BackoffPolicy:
    """Jittered exponential backoff: ``delay(attempt)`` for attempt 0,1,2...

    ``None`` parameters resolve from the config registry at construction.
    A seeded policy produces a deterministic jitter sequence (chaos tests);
    unseeded policies share the process RNG.
    """

    __slots__ = ("base_s", "max_s", "multiplier", "jitter", "_rng")

    def __init__(self, base_s: Optional[float] = None,
                 max_s: Optional[float] = None,
                 multiplier: Optional[float] = None,
                 jitter: Optional[float] = None,
                 seed: Optional[int] = None):
        self.base_s = config.retry_backoff_base_s if base_s is None else base_s
        self.max_s = config.retry_backoff_max_s if max_s is None else max_s
        self.multiplier = (config.retry_backoff_multiplier
                           if multiplier is None else multiplier)
        self.jitter = (config.retry_backoff_jitter
                       if jitter is None else jitter)
        self._rng = random.Random(seed) if seed is not None else random

    def delay(self, attempt: int) -> float:
        """Backoff delay for the given 0-based attempt number."""
        d = min(self.max_s,
                self.base_s * (self.multiplier ** max(0, attempt)))
        if self.jitter > 0:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)

    def stagger(self, span_s: float) -> float:
        """A full-range uniform draw in ``[0, span_s]`` — the FIRST-attempt
        de-synchronizer.  ``delay()``'s +/- jitter keeps retry LOOPS from
        re-synchronizing, but when many processes start their loops at the
        same instant (every raylet sees the GCS socket die simultaneously
        on a restart) a fractional jitter still concentrates the herd
        around the shared base delay; a full-span draw spreads the initial
        dials/registrations evenly across the window instead."""
        if span_s <= 0:
            return 0.0
        return self._rng.uniform(0.0, span_s)
