"""Cluster-wide continuous profiling: sampling stacks + live introspection.

Reference analogue: the dashboard's py-spy integration (``ray stack`` /
the per-worker "CPU flame graph" button, `dashboard/modules/reporter/
reporter_agent.py`) and ``ray stack``'s all-thread dumps.  Re-designed
in-process: instead of attaching an external tracer, every ray_tpu
process (worker, raylet, GCS, driver) runs ONE sampling daemon thread
that walks ``sys._current_frames()`` at ``RAY_TPU_PROFILE_HZ`` and folds
each thread's stack into collapsed-stack counts — the flamegraph.pl /
speedscope "folded" format — tagged with the currently-executing task id
/ trace id / actor id (wired through the execution context the tracing
layer already propagates), so flamegraphs can be sliced per request hop,
per actor, or per Serve deployment.

Three consumers feed off this module:

* **Continuous profiles**: folded counts batch-flush toward the per-node
  GCS profile table on the task-event cadence (bounded buffers, oldest
  dropped and counted, ``RAY_TPU_PROFILE=0`` is a live kill switch) —
  ``state.profile(duration_s)`` / ``ray_tpu profile`` / dashboard
  ``/api/profile`` read it back and export speedscope / collapsed text.
* **Live stacks**: ``dump_threads()`` snapshots every thread's current
  stack (plus its task/trace tags) on demand — the payload behind
  ``ray_tpu stack``, ``state.list_stacks`` and dashboard ``/api/stacks``,
  the ``py-spy dump`` analogue that works on a live remote process
  because the dump runs *inside* it, relayed over the existing protocol.
* **The sampler itself is the overhead budget**: a pure-Python walker at
  the default 19 Hz costs well under the 3% bench bar (``profile_overhead``
  row in bench_core), and the kill switch reduces it to a 0.5 s idle poll.

Samples are wall-clock samples of ON-CPU *and* blocked threads (like
``py-spy --idle``): for a control plane the interesting question is
usually "what is this thread waiting on", which on-CPU-only profilers
erase.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock

config.define("profile", bool, True,
              "Continuous-profiling master switch (live): every process "
              "samples its threads' stacks at profile_hz into the GCS "
              "profile table.  RAY_TPU_PROFILE=0 is a cluster-wide "
              "runtime kill switch — the sampler thread idles.", live=True)
config.define("profile_hz", float, 19.0,
              "Stack-sampling rate of the in-process profiler (live).  A "
              "prime default avoids lockstep aliasing with periodic work; "
              "raise it for a sharper capture window, at sampling cost.",
              live=True)
config.define("profile_max_depth", int, 64,
              "Deepest stack recorded per sample; frames below the cutoff "
              "are folded into a '...' root segment.")
config.define("profile_buffer_size", int, 4096,
              "Per-process cap on not-yet-flushed folded sample records; "
              "overflow drops the OLDEST records and counts them — the "
              "sampler never blocks or grows without bound.")
config.define("profile_flush_interval_s", float, 1.0,
              "Folded-profile batch-flush period (worker -> raylet -> GCS "
              "profile table).")
config.define("profile_table_max", int, 50000,
              "GCS-side profile-table cap per node: oldest sample records "
              "evicted first, evictions counted in profile_table_stats.")

__all__ = ["ensure_profiler", "profiling_enabled", "set_task_tags",
           "reset_task_tags", "dump_threads", "drain_samples",
           "set_flush_target", "flush_samples", "to_speedscope",
           "to_collapsed", "summarize"]


# ------------------------------------------------------------------ state

_proc_label = "driver"
_sampler: Optional[threading.Thread] = None  # guard: _lock
_lock = make_lock("profiling.state")
# thread ident -> (task_id, trace_id, actor_id, task_name): written by the
# executing thread around each task, read (racily, by design — a torn read
# just mis-tags one sample) by the sampler thread.
_task_tags: Dict[int, tuple] = {}

# Folded aggregation window: (thread_name, folded_stack, tags) -> count,
# reset at each drain.  Only the sampler thread writes counts; drains swap
# the dict out under the lock.
_counts: Dict[tuple, int] = {}  # guard: _lock
_window_t0 = 0.0                # guard: _lock
_samples_total = 0              # guard: _lock — lifetime, for stats/tests

from collections import deque as _deque

# Drained-but-not-shipped records (bounded; oldest dropped + counted).
_pending: "deque" = _deque()  # guard: _lock
_dropped = 0               # guard: _lock
_flush_fn: Optional[Callable[[List[dict], int], None]] = None
_flusher_started = False   # guard: _lock

# frame -> "name (file:line)" label cache: code objects are interned per
# function, so this collapses the per-sample formatting cost to a dict
# hit.  Bounded — dynamically minted code (exec, lambdas in loops) must
# not grow it forever.
_label_cache: Dict[tuple, str] = {}
_LABEL_CACHE_CAP = 8192

# Live-flag cache (same trick as tracing._live_flags): the sampler ticks
# profile_hz times a second and a registry read costs ~3us.
_live = {"at": -1.0, "on": False, "hz": 19.0}


def _live_flags() -> dict:
    now = time.monotonic()
    if now - _live["at"] > 0.25:
        _live["on"] = config.profile
        _live["hz"] = config.profile_hz
        _live["at"] = now
    return _live


def profiling_enabled() -> bool:
    """The live master switch — RAY_TPU_PROFILE=0 idles every sampler in
    the cluster within one flag-cache tick, no restarts."""
    return _live_flags()["on"]


def set_process_label(label: str):
    """Sample attribution: 'driver' | 'worker' | 'raylet' | 'gcs'."""
    global _proc_label
    _proc_label = label


# ------------------------------------------------------------------- tags


def set_task_tags(task_id: Optional[str] = None,
                  trace_id: Optional[str] = None,
                  actor_id: Optional[str] = None,
                  name: Optional[str] = None, chain: bool = True):
    """Mark the calling thread as executing ``task_id`` so samples taken
    while it runs carry the attribution.  Returns a token for
    ``reset_task_tags``.  ``chain=False`` is for tasks SHARING a thread
    (asyncio actors interleave on the loop thread): the reset then clears
    rather than restores, so a task finishing out of LIFO order can't
    resurrect an already-finished task's tags onto the idle thread."""
    ident = threading.get_ident()
    prev = _task_tags.get(ident) if chain else None
    mine = (task_id, trace_id, actor_id, name)
    _task_tags[ident] = mine
    return (prev, mine)


def reset_task_tags(token):
    """Undo ``set_task_tags`` — only if this thread's tags are still the
    ones that call installed (on a shared asyncio thread a later task may
    have re-tagged it; its attribution must survive our exit)."""
    if token is None:
        return
    prev, mine = token
    ident = threading.get_ident()
    if _task_tags.get(ident) is not mine:
        return
    if prev is None:
        _task_tags.pop(ident, None)
    else:
        _task_tags[ident] = prev


# ---------------------------------------------------------------- sampling


def _frame_label(code, lineno: int) -> str:
    key = (code, lineno)
    label = _label_cache.get(key)
    if label is None:
        fname = code.co_filename
        base = fname.rsplit("/", 1)[-1]
        label = f"{code.co_name} ({base}:{lineno})"
        if len(_label_cache) >= _LABEL_CACHE_CAP:
            _label_cache.clear()
        _label_cache[key] = label
    return label


def _fold(frame, max_depth: int) -> str:
    """Collapse one thread's frame chain into 'root;...;leaf'."""
    parts: List[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        parts.append(_frame_label(frame.f_code, frame.f_lineno))
        frame = frame.f_back
        depth += 1
    if frame is not None:
        parts.append("...")
    parts.reverse()
    return ";".join(parts)


def _sample_once(own_ident: int, names: Dict[int, str], max_depth: int):
    global _samples_total
    try:
        frames = sys._current_frames()
    except RuntimeError:  # interpreter tearing down
        return
    keys = []
    for ident, frame in frames.items():
        if ident == own_ident:
            continue
        stack = _fold(frame, max_depth)
        tags = _task_tags.get(ident)
        keys.append((names.get(ident) or f"thread-{ident}", stack, tags))
    with _lock:
        counts = _counts
        for key in keys:
            counts[key] = counts.get(key, 0) + 1
        _samples_total += len(keys)


def _sampler_loop():
    global _window_t0
    own_ident = threading.get_ident()
    max_depth = config.profile_max_depth
    while True:
        flags = _live_flags()
        if not flags["on"]:
            time.sleep(0.5)  # blocking-ok: dedicated sampler thread
            continue
        time.sleep(1.0 / max(0.5, flags["hz"]))  # blocking-ok: dedicated sampler thread
        try:
            # fresh name map EVERY tick: thread idents are recycled, so a
            # cached map can attribute a new thread's stack to a dead
            # thread's name (enumerate is O(threads) — cheap at any hz)
            names = {t.ident: t.name for t in threading.enumerate()
                     if t.ident is not None}
            with _lock:
                if _window_t0 == 0.0:
                    _window_t0 = time.time()
            _sample_once(own_ident, names, max_depth)
        except Exception:  # noqa: BLE001 — the sampler must survive anything
            pass


def ensure_profiler(label: Optional[str] = None) -> bool:
    """Start this process's sampling thread (idempotent).  Safe to call
    with profiling disabled — the thread idles until the live switch
    flips on.  Returns True when a sampler is running after the call."""
    global _sampler
    if label is not None:
        set_process_label(label)
    with _lock:
        if _sampler is not None and _sampler.is_alive():
            return True
        _sampler = t = threading.Thread(target=_sampler_loop,
                                        name="profile-sampler", daemon=True)
    t.start()
    return True


# ------------------------------------------------------------------ drain


def drain_samples() -> Tuple[List[dict], int]:
    """Fold the current window into sample records, take everything
    pending, and return ``(records, dropped_since_last_drain)``.  Fed by
    the raylet's flush cadence and the worker/client flusher thread."""
    global _dropped
    _roll_window()
    with _lock:
        if not _pending and not _dropped:
            return [], 0
        records = list(_pending)
        _pending.clear()
        dropped, _dropped = _dropped, 0
    return records, dropped


def _roll_window():
    """Convert the active counting window into pending records (bounded).
    Called from drain paths and the flusher."""
    global _window_t0, _dropped
    t1 = time.time()
    with _lock:
        if not _counts:
            return
        items = list(_counts.items())
        _counts.clear()
        t0 = _window_t0 or t1
        _window_t0 = 0.0
        cap = config.profile_buffer_size
        for (tname, stack, tags), n in items:
            task_id, trace_id, actor_id, task_name = tags or (None,) * 4
            rec = {"thread": tname, "stack": stack, "count": n,
                   "t0": t0, "t1": t1, "pid": os.getpid(),
                   "proc": _proc_label, "node": config.node_id[:12]}
            if task_id:
                rec["task"] = task_id
            if trace_id:
                rec["trace"] = trace_id
            if actor_id:
                rec["actor"] = actor_id
            if task_name:
                rec["name"] = task_name
            _pending.append(rec)
        while len(_pending) > cap:
            _pending.popleft()
            _dropped += 1


def set_flush_target(fn: Optional[Callable[[List[dict], int], None]]):
    """Register the batch shipper for processes with no in-process raylet
    (workers, TCP client drivers, the standalone GCS) and start the
    cadence flusher — mirrors ``tracing.set_flush_target``."""
    global _flush_fn, _flusher_started
    _flush_fn = fn
    if fn is None:
        return
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(target=_flush_loop, name="profile-flush",
                     daemon=True).start()


def _flush_loop():
    while True:
        time.sleep(max(0.1, config.profile_flush_interval_s))  # blocking-ok: dedicated flusher thread
        try:
            flush_samples()
        except Exception:  # noqa: BLE001 — flusher must live
            pass


def flush_samples():
    """Ship pending records through the registered flush target now (no-op
    without one — the raylet drains the buffer directly in that case)."""
    fn = _flush_fn
    if fn is None:
        return
    records, dropped = drain_samples()
    if records or dropped:
        fn(records, dropped)


def stats() -> dict:
    with _lock:
        return {"samples_total": _samples_total,
                "pending": len(_pending), "dropped": _dropped,
                "window_open": _window_t0 != 0.0}


# ----------------------------------------------------------- live stacks


def dump_threads(proc: Optional[str] = None) -> List[dict]:
    """Every thread's current stack, name, and task tags — the live
    introspection payload behind ``ray_tpu stack`` (the ``py-spy dump``
    analogue, run in-process and relayed over the protocol)."""
    frames = sys._current_frames()
    infos = {t.ident: t for t in threading.enumerate()
             if t.ident is not None}
    out = []
    own = threading.get_ident()
    for ident, frame in frames.items():
        t = infos.get(ident)
        tags = _task_tags.get(ident)
        entry = {
            "name": t.name if t is not None else f"thread-{ident}",
            "ident": ident,
            "daemon": bool(t.daemon) if t is not None else None,
            "current": ident == own,
            "proc": proc or _proc_label,
            "pid": os.getpid(),
            "frames": _fold(frame, config.profile_max_depth).split(";"),
        }
        if tags is not None:
            task_id, trace_id, actor_id, task_name = tags
            if task_id:
                entry["task"] = task_id
            if trace_id:
                entry["trace"] = trace_id
            if actor_id:
                entry["actor"] = actor_id
            if task_name:
                entry["task_name"] = task_name
        out.append(entry)
    out.sort(key=lambda e: e["name"])
    return out


def format_stacks(threads: List[dict]) -> str:
    """Human-readable rendering of ``dump_threads`` output (CLI)."""
    lines = []
    for t in threads:
        tag = ""
        if t.get("task"):
            tag = f"  [task={t['task'][:12]}"
            if t.get("task_name"):
                tag += f" {t['task_name']}"
            if t.get("trace"):
                tag += f" trace={t['trace'][:12]}"
            tag += "]"
        lines.append(f"  {t['name']} (ident={t['ident']}"
                     f"{', daemon' if t.get('daemon') else ''}){tag}")
        for fr in t["frames"]:
            lines.append(f"    {fr}")
    return "\n".join(lines)


# ---------------------------------------------------------------- exports


def to_collapsed(samples: List[dict],
                 include_thread: bool = True) -> str:
    """flamegraph.pl collapsed format: one ``a;b;c count`` line per
    distinct folded stack, counts merged across sample records."""
    agg: Dict[str, int] = {}
    for rec in samples:
        stack = rec.get("stack", "")
        if include_thread:
            stack = f"{rec.get('proc', '?')}:{rec.get('thread', '?')};" \
                + stack
        agg[stack] = agg.get(stack, 0) + int(rec.get("count", 0))
    return "\n".join(f"{stack} {n}"
                     for stack, n in sorted(agg.items())) + "\n"


def to_speedscope(samples: List[dict], name: str = "ray_tpu profile") -> dict:
    """speedscope's JSON file format (sampled profile): load the result at
    https://www.speedscope.app or with `speedscope file.json`.  Weights
    are sample counts (unit "none"); each folded stack becomes one
    sampled entry, root-first frame indices into the shared frame list."""
    frame_idx: Dict[str, int] = {}
    frames: List[dict] = []
    sample_rows: List[List[int]] = []
    weights: List[int] = []
    agg: Dict[tuple, int] = {}
    for rec in samples:
        key = (rec.get("proc", "?"), rec.get("thread", "?"),
               rec.get("stack", ""))
        agg[key] = agg.get(key, 0) + int(rec.get("count", 0))
    for (proc, thread, stack), n in sorted(agg.items()):
        row = []
        for label in (f"{proc}:{thread}", *stack.split(";")):
            idx = frame_idx.get(label)
            if idx is None:
                idx = frame_idx[label] = len(frames)
                frames.append({"name": label})
            row.append(idx)
        sample_rows.append(row)
        weights.append(n)
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "exporter": "ray_tpu",
        "name": name,
        "activeProfileIndex": 0,
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": sample_rows,
            "weights": weights,
        }],
    }


def summarize(samples: List[dict], top: int = 30) -> dict:
    """The "where does the CPU go" table: per-function self and inclusive
    sample counts (plus per-process and per-task slices) over a batch of
    profile-table records — the profiling analogue of
    ``trace_analysis.aggregate``."""
    self_counts: Dict[str, int] = {}
    total_counts: Dict[str, int] = {}
    by_proc: Dict[str, int] = {}
    by_task: Dict[str, int] = {}
    total = 0
    for rec in samples:
        n = int(rec.get("count", 0))
        total += n
        by_proc[rec.get("proc", "?")] = \
            by_proc.get(rec.get("proc", "?"), 0) + n
        task = rec.get("task")
        if task:
            by_task[task] = by_task.get(task, 0) + n
        frames = rec.get("stack", "").split(";")
        if frames and frames[-1]:
            self_counts[frames[-1]] = self_counts.get(frames[-1], 0) + n
        for fr in set(frames):
            if fr:
                total_counts[fr] = total_counts.get(fr, 0) + n

    def table(counts: Dict[str, int]) -> List[dict]:
        rows = sorted(counts.items(), key=lambda kv: -kv[1])[:top]
        return [{"frame": fr, "samples": n,
                 "share": round(n / total, 4) if total else 0.0}
                for fr, n in rows]

    return {
        "total_samples": total,
        "num_records": len(samples),
        "by_proc": dict(sorted(by_proc.items(), key=lambda kv: -kv[1])),
        "num_tagged_tasks": len(by_task),
        "top_self": table(self_counts),
        "top_total": table(total_counts),
    }
