"""Lock factory + runtime lock-order watchdog.

Reference analogue: the reference leans on clang thread-safety annotations
(``GUARDED_BY``/``ACQUIRED_AFTER`` in `src/ray/common/`) for compile-time
lock discipline; a Python port gets no compiler help, so the dynamic half
lives here and the static half in ``tools/analysis``.

Every lock in the concurrent core is created through :func:`make_lock` /
:func:`make_rlock` with a stable dotted name (``"raylet.inbox"``,
``"pull_manager.state"``).  Normally that returns a plain
``threading.Lock`` — zero overhead.  With ``RAY_TPU_DEBUG_LOCKS=1`` it
returns a :class:`DebugLock` that

* keeps a per-thread stack of locks currently held,
* records every observed acquisition ORDER (lock A held while acquiring
  lock B) as an edge A->B in a process-global graph, stamped with the
  stack trace that first exhibited it, and
* checks the graph for cycles ONLINE, before blocking on the inner
  acquire: the moment any thread's acquisition would close a cycle
  (A->...->B observed earlier, B->A now), the potential deadlock is
  reported with both stacks — even if the threads never actually race.

Violations are collected in-process (:func:`lock_order_violations`) and
printed to stderr once per distinct cycle.  The CI workflow runs the fast
test subset with the watchdog on.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.config import config

__all__ = ["DebugLock", "make_lock", "make_rlock", "lock_order_violations",
           "reset_lock_order_state"]

# Process-global acquisition-order graph.  _edges is only ever mutated
# under _graph_lock; readers use GIL-atomic dict membership checks on the
# hot path so an already-known edge costs one dict probe, no lock.
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], List[str]] = {}  # guard: _graph_lock
_succ: Dict[str, set] = {}                     # guard: _graph_lock
_violations: List[dict] = []                   # guard: _graph_lock
_reported: set = set()                         # guard: _graph_lock
_held = threading.local()  # .stack — this thread's currently-held DebugLocks


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """BFS for a path src -> ... -> dst through the order graph (caller
    holds _graph_lock)."""
    if src == dst:
        return [src]
    parents: Dict[str, str] = {src: src}
    frontier = [src]
    while frontier:
        nxt = []
        for node in frontier:
            for succ in _succ.get(node, ()):  # unguarded-ok: documented — caller holds _graph_lock (requires below)
                if succ in parents:
                    continue
                parents[succ] = node
                if succ == dst:
                    path = [dst]
                    while path[-1] != src:
                        path.append(parents[path[-1]])
                    return path[::-1]
                nxt.append(succ)
        frontier = nxt
    return None


def lock_order_violations() -> List[dict]:
    """Potential deadlocks observed so far: each entry has ``cycle`` (the
    lock names around the loop) and ``stacks`` (one formatted stack per
    edge of the cycle — "both stacks" for the two-lock ABBA case)."""
    with _graph_lock:
        return [dict(v) for v in _violations]


def reset_lock_order_state():
    """Forget every recorded edge and violation (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _succ.clear()
        _violations.clear()
        _reported.clear()


class DebugLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper feeding the order graph.

    The ordering edge is recorded (and the cycle check runs) BEFORE the
    blocking inner acquire: a live ABBA deadlock reports at the moment it
    forms instead of hanging silently, and two orderings observed at
    different times still flag the latent cycle.
    """

    __slots__ = ("name", "_inner", "_reentrant")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held_stack()
        first_entry = not (self._reentrant
                           and any(e is self for e in held))
        # Ordering discipline applies to BLOCKING first acquisitions only:
        # a try-acquire cannot deadlock, and a reentrant re-acquire adds no
        # new ordering.
        if blocking and first_entry:
            for prev in held:
                self._note_edge(prev.name, self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            held.append(self)
        return got

    def release(self):
        self._inner.release()
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self) -> bool:
        # RLock has no locked() before 3.12; DebugLock is only asked by
        # plain-lock call sites.
        return self._inner.locked()

    def _note_edge(self, a: str, b: str):
        if a == b:
            # Two same-named instances nested (e.g. two peers' send locks):
            # instance-level order is not tracked across a shared name.
            return
        if (a, b) in _edges:  # unguarded-ok: GIL-atomic membership probe, rechecked under _graph_lock below
            return
        stack = "".join(traceback.format_stack(limit=16)[:-2])
        with _graph_lock:
            if (a, b) in _edges:
                return
            # Closing edge a->b while b ->...-> a already exists = cycle.
            path = _find_path(b, a)
            _edges[(a, b)] = [stack]
            _succ.setdefault(a, set()).add(b)
            if path is None:
                return
            cycle = [a] + path  # a -> b -> ... -> a
            key = frozenset(cycle)
            if key in _reported:
                return
            _reported.add(key)
            stacks = [f"--- edge {a} -> {b} (this thread,"
                      f" {threading.current_thread().name}):\n{stack}"]
            for i in range(len(path) - 1):
                estack = _edges.get((path[i], path[i + 1]))
                if estack:
                    stacks.append(f"--- edge {path[i]} -> {path[i + 1]} "
                                  f"(first observed):\n{estack[0]}")
            _violations.append({"cycle": cycle, "stacks": stacks})
            sys.stderr.write(
                "[ray_tpu][debug-locks] POTENTIAL DEADLOCK: lock order "
                "cycle " + " -> ".join(cycle) + "\n"
                + "\n".join(stacks) + "\n")


def make_lock(name: str):
    """A lock for runtime shared state: plain ``threading.Lock`` normally,
    order-tracked :class:`DebugLock` under ``RAY_TPU_DEBUG_LOCKS=1``."""
    if config.debug_locks:
        return DebugLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """Reentrant variant of :func:`make_lock`."""
    if config.debug_locks:
        return DebugLock(name, reentrant=True)
    return threading.RLock()
