"""User metrics API: Counter / Gauge / Histogram.

Reference analogue: `python/ray/util/metrics.py` (Counter `:150`,
Histogram `:215`, Gauge `:290`) backed by the C++ OpenCensus registry and a
per-node MetricsAgent re-exporting Prometheus
(`python/ray/_private/metrics_agent.py:375`).

TPU-first re-design: no per-node agent processes — each worker process
batches its metric samples and flushes them to the GCS KV (namespace
``metrics``, key ``<pid-uuid>/<metric>``); the dashboard's ``/metrics``
endpoint merges every producer's samples into one Prometheus text page
(counters sum, gauges take the latest write, histogram buckets add).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock

__all__ = ["Counter", "Gauge", "Histogram", "flush_metrics",
           "shutdown_metrics", "render_kv_metrics", "merge_kv_metrics",
           "kv_metrics_json", "render_prom_lines", "internal_metric",
           "INTERNAL_PREFIX", "PointRing", "collect_points",
           "set_points_target", "record_points", "drain_points"]

config.define("metrics_flush_s", float, 1.0,
              "Per-process user-metric flush period into the GCS metrics "
              "KV (the dashboard's /metrics merges every producer).")
config.define("metrics_history", bool, True,
              "Time-series export: every metric flush also ships "
              "timestamped DELTA points into the GCS metrics time-series "
              "table (add_metric_points), queryable via state.query_metrics"
              " / `ray_tpu metrics`.  RAY_TPU_METRICS_HISTORY=0 keeps only "
              "the instantaneous snapshot KV.")
config.define("metrics_history_ring", int, 4096,
              "Per-process ring-buffer cap for metric points awaiting "
              "export; overflow drops the OLDEST points and counts them "
              "(export backpressure never blocks recording).")

_NS = "metrics"
_FLUSH_INTERVAL_S = config.metrics_flush_s

# Metric names under this prefix are reserved for the runtime's own
# instrumentation (scheduler queue depth, dispatch latency, ...) — user
# metrics may not claim them (reference: the ray_* internal namespace,
# `metrics_agent.py:375`).  Internal metrics are built via internal_metric()
# and flushed by their owner (e.g. the raylet, which has no global worker
# in cluster mode), not by the per-process flusher thread.
INTERNAL_PREFIX = "ray_tpu_internal_"

_registry_lock = make_lock("metrics.registry")
_registry: List["Metric"] = []  # guard: _registry_lock
_producer_id = f"{os.getpid()}-{uuid.uuid4().hex[:6]}"  # guard: _registry_lock
_flusher_started = False  # guard: _registry_lock
_flusher_stop = threading.Event()
_mk_internal = threading.local()


def _kv_put(key: bytes, value: bytes) -> bool:
    from ray_tpu.core import worker as worker_mod

    w = worker_mod._global_worker  # raw slot: may be None before init
    if w is None:
        return False
    try:
        w.kv_put(key, value, namespace=_NS)
        return True
    except Exception:  # noqa: BLE001
        return False


def _ensure_flusher():
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True
        stop = _flusher_stop

    def loop():
        while not stop.wait(_FLUSH_INTERVAL_S):
            try:
                flush_metrics()
            except Exception:  # noqa: BLE001
                pass
            try:
                flush_points()
            except Exception:  # noqa: BLE001
                pass

    threading.Thread(target=loop, name="metrics-flush", daemon=True).start()


def flush_metrics():
    """Push every registered metric's samples to the GCS KV now.  One
    broken metric must not starve the rest of the registry — exports are
    isolated per metric."""
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        try:
            payload = m._export()
        except Exception:  # noqa: BLE001 — defensive: skip, don't starve
            continue
        if payload is None:
            continue
        # unguarded-ok: GIL-atomic str read; rotation only happens in
        # shutdown_metrics, where a stale id at worst double-keys one final
        # sample window (normal Prometheus counter-reset semantics).
        _kv_put(f"{_producer_id}/{m.name}".encode(),
                json.dumps(payload).encode())


def shutdown_metrics():
    """End-of-session metrics teardown, called from ``ray_tpu.shutdown()``:

    * final SYNCHRONOUS flush — the daemon flusher would otherwise lose
      every sample recorded in the last ``RAY_TPU_METRICS_FLUSH_S`` window;
    * stop the flusher thread and reset ``_flusher_started`` so the next
      ``init()`` in this process starts a fresh one;
    * rotate ``_producer_id`` and clear accumulated samples so a re-init
      against the SAME GCS does not double-report the finished session's
      counters under two producer keys (counter resets are normal
      Prometheus semantics).
    """
    global _flusher_started, _producer_id, _flusher_stop, _points_ring
    global _points_target
    try:
        flush_metrics()
    except Exception:  # noqa: BLE001
        pass
    try:
        flush_points()
    except Exception:  # noqa: BLE001
        pass
    _flusher_stop.set()
    with _registry_lock:
        _flusher_started = False
        _flusher_stop = threading.Event()
        _producer_id = f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        metrics = list(_registry)
        _points_ring = None
    _points_target = None
    _points_last.clear()
    for m in metrics:
        with m._lock:
            getattr(m, "_values", {}).clear()


# ----------------------------------------------------- time-series points
#
# Each flush cadence also emits timestamped DELTA points (counters and
# histograms ship increments over the interval, gauges ship the current
# value when it changes) into a bounded per-process ring; the owner of the
# process exports the ring into the GCS metrics time-series table
# (add_metric_points) — workers via "metric_points" control frames to their
# raylet, the raylet/GCS directly on their own flush cadence.  Shipping
# deltas (not cumulative snapshots) makes the table mergeable across
# producer restarts and makes rate()/quantile-over-window pure sums.


class PointRing:
    """Bounded ring of metric points awaiting export.  Overflow evicts the
    OLDEST point and counts it; a failed flush requeues its batch so the
    data survives a dropped flush (bounded by the same cap)."""

    def __init__(self, cap: int):
        self._cap = max(1, int(cap))
        self._buf: collections.deque = collections.deque()  # guard: _lock
        self._dropped = 0  # guard: _lock
        self._lock = make_lock("metrics.points")

    def add(self, points: Sequence[dict]):
        with self._lock:
            for p in points:
                if len(self._buf) >= self._cap:
                    self._buf.popleft()
                    self._dropped += 1
                self._buf.append(p)

    def drain(self) -> Tuple[List[dict], int]:
        """Remove and return ``(points, dropped)``.  The caller owns the
        batch; on a failed hand-off it should ``requeue`` it."""
        with self._lock:
            points = list(self._buf)
            self._buf.clear()
            dropped, self._dropped = self._dropped, 0
            return points, dropped

    def requeue(self, points: Sequence[dict], dropped: int = 0):
        """Put a failed flush's batch back at the FRONT of the ring (its
        points are older than anything recorded since), evicting from the
        front when the cap would overflow — delta points lost this way are
        counted, never silently re-baselined."""
        with self._lock:
            self._dropped += dropped
            room = self._cap - len(self._buf)
            batch = list(points)
            if len(batch) > room:
                self._dropped += len(batch) - room
                batch = batch[len(batch) - room:] if room > 0 else []
            self._buf.extendleft(reversed(batch))

    def __len__(self):
        with self._lock:
            return len(self._buf)


def collect_points(metrics, last: Dict, ts: Optional[float] = None
                   ) -> List[dict]:
    """Compute timestamped delta points for ``metrics`` against the
    baseline dict ``last`` (mutated in place; key ``(name, tag_key)``).

    Counters emit their increment since the previous call, histograms the
    per-bucket/sum/count increments, gauges the current value whenever it
    differs from the last emitted one.  Quiet series emit nothing — the
    time-series table only grows when something happens."""
    now = time.time() if ts is None else ts
    points: List[dict] = []
    for m in metrics:
        with m._lock:
            values = {k: (list(v) if isinstance(v, list) else v)
                      for k, v in getattr(m, "_values", {}).items()}
        if isinstance(m, Counter):
            for key, value in values.items():
                base = last.get((m.name, key), 0.0)
                delta = value - base
                if delta > 0:
                    last[(m.name, key)] = value
                    points.append({"name": m.name, "kind": "counter",
                                   "tags": [list(t) for t in key],
                                   "ts": now, "value": delta})
        elif isinstance(m, Gauge):
            for key, (value, _vts) in values.items():
                if last.get((m.name, key)) != value:
                    last[(m.name, key)] = value
                    points.append({"name": m.name, "kind": "gauge",
                                   "tags": [list(t) for t in key],
                                   "ts": now, "value": value})
        elif isinstance(m, Histogram):
            for key, rec in values.items():
                base = last.get((m.name, key))
                if base is None:
                    delta = list(rec)
                else:
                    delta = [a - b for a, b in zip(rec, base)]
                if delta[-1] > 0:  # count increment this interval
                    last[(m.name, key)] = list(rec)
                    points.append({"name": m.name, "kind": "histogram",
                                   "tags": [list(t) for t in key],
                                   "ts": now, "value": delta,
                                   "bounds": list(m.boundaries)})
    return points


_points_ring: Optional[PointRing] = None  # guard: _registry_lock (creation)
_points_last: Dict = {}  # baselines; only touched by the flusher/raylet tick
_points_target: Optional[Callable[[List[dict], int], None]] = None


def _ring() -> PointRing:
    global _points_ring
    with _registry_lock:
        if _points_ring is None:
            _points_ring = PointRing(config.metrics_history_ring)
        return _points_ring


def set_points_target(fn: Optional[Callable[[List[dict], int], None]]):
    """Register the export hand-off for this process's metric points
    (worker processes: a ``metric_points`` control frame to the raylet).
    Without a target the ring just accumulates — the in-process raylet
    drains it on its own flush cadence (driver mode)."""
    global _points_target
    _points_target = fn


def record_points(ts: Optional[float] = None):
    """Snapshot registered metrics' deltas into the point ring."""
    if not config.metrics_history:
        return
    with _registry_lock:
        metrics = list(_registry)
    pts = collect_points(metrics, _points_last, ts)
    if pts:
        _ring().add(pts)


def drain_points() -> Tuple[List[dict], int]:
    """Drain the pending point ring — used by the in-process raylet, which
    ships the batch inside its own add_metric_points post."""
    # unguarded-ok: _points_ring is write-once (created under
    # _registry_lock, never reset); PointRing itself is internally locked
    if _points_ring is None:
        return [], 0
    return _points_ring.drain()  # unguarded-ok: see above


def flush_points():
    """Record this interval's deltas and, when a target is registered,
    hand the ring's contents off; a failed hand-off requeues the batch so
    one dropped flush loses nothing (the ring cap bounds the debt)."""
    record_points()
    target = _points_target
    # unguarded-ok: _points_ring is write-once (created under
    # _registry_lock, never reset); PointRing itself is internally locked
    if target is None or _points_ring is None:
        return
    points, dropped = _points_ring.drain()  # unguarded-ok: see above
    if not points and not dropped:
        return
    try:
        target(points, dropped)
    except Exception:  # noqa: BLE001 — transport hiccup: retry next tick
        _points_ring.requeue(points, dropped)  # unguarded-ok: see above


def internal_metric(cls, name: str, *args, register: bool = False,
                    **kwargs):
    """Construct a runtime-internal metric: the reserved
    ``ray_tpu_internal_`` prefix is allowed (enforced on the name).  By
    default the instance is NOT registered with the per-process flusher —
    the owning component exports it explicitly (see
    ``Raylet._flush_internal_metrics``, which works even in raylet
    processes that have no global worker).  ``register=True`` keeps the
    reserved name but hands export to the normal per-process flusher —
    for internal series owned by ordinary worker/driver processes (the
    Serve router/replica/proxy telemetry)."""
    if not name.startswith(INTERNAL_PREFIX):
        name = INTERNAL_PREFIX + name
    _mk_internal.on = True
    _mk_internal.register = register
    try:
        return cls(name, *args, **kwargs)
    finally:
        _mk_internal.on = False
        _mk_internal.register = False


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name or any(c in name for c in " \n\t"):
            raise ValueError(f"invalid metric name {name!r}")
        internal = getattr(_mk_internal, "on", False)
        if name.startswith(INTERNAL_PREFIX) and not internal:
            raise ValueError(
                f"metric name prefix {INTERNAL_PREFIX!r} is reserved for "
                "runtime-internal metrics")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._default_key: Tuple = ()
        self._lock = make_lock("metrics.metric")
        if not internal or getattr(_mk_internal, "register", False):
            with _registry_lock:
                _registry.append(self)
            _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]):
        unknown = set(tags) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"tags {unknown} not in tag_keys")
        self._default_tags = dict(tags)
        self._default_key = tuple(sorted(self._default_tags.items()))
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]) -> Tuple:
        if tags is None:
            # Hot path (per-observation internal metrics): the default-tag
            # key is precomputed — no dict copy/sort per sample.
            return self._default_key
        merged = dict(self._default_tags)
        merged.update(tags)
        unknown = set(merged) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"tags {unknown} not in tag_keys "
                             f"{self.tag_keys}")
        return tuple(sorted(merged.items()))

    def _export(self) -> Optional[dict]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonic cumulative count (reference `metrics.py:150`)."""

    def __init__(self, name, description: str = "", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}  # guard: _lock

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() takes a non-negative value")
        key = self._resolve_tags(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def _export(self):
        with self._lock:
            if not self._values:
                return None
            return {"type": "counter", "desc": self.description,
                    "samples": [[list(k), v]
                                for k, v in self._values.items()]}


class Gauge(Metric):
    """Point-in-time value (reference `metrics.py:290`)."""

    def __init__(self, name, description: str = "", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, Tuple[float, float]] = {}  # guard: _lock

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._resolve_tags(tags)
        with self._lock:
            self._values[key] = (float(value), time.time())

    def _export(self):
        with self._lock:
            if not self._values:
                return None
            return {"type": "gauge", "desc": self.description,
                    "samples": [[list(k), v, ts]
                                for k, (v, ts) in self._values.items()]}


class Histogram(Metric):
    """Bucketed distribution (reference `metrics.py:215`)."""

    def __init__(self, name, description: str = "",
                 boundaries: Optional[Sequence[float]] = None, tag_keys=None):
        # Validate BEFORE super().__init__: the base class registers the
        # metric with the flusher, so raising after it would leave a
        # half-constructed entry in the registry whose _export crashes
        # every later flush (and silently starves the metrics registered
        # after it — an ordering-dependent whole-suite failure).
        bounds = sorted(boundaries or (0.1, 1.0, 10.0, 100.0))
        if any(b <= 0 for b in bounds):
            raise ValueError("histogram boundaries must be positive")
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(bounds)
        # key -> [bucket_counts..., +inf_count, sum, count]
        self._values: Dict[Tuple, list] = {}  # guard: _lock

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._resolve_tags(tags)
        with self._lock:
            rec = self._values.get(key)
            if rec is None:
                rec = [0] * (len(self.boundaries) + 1) + [0.0, 0]
                self._values[key] = rec
            idx = next((i for i, b in enumerate(self.boundaries)
                        if value <= b), len(self.boundaries))
            rec[idx] += 1
            rec[-2] += float(value)
            rec[-1] += 1

    def _export(self):
        with self._lock:
            if not self._values:
                return None
            return {"type": "histogram", "desc": self.description,
                    "bounds": list(self.boundaries),
                    "samples": [[list(k), list(v)]
                                for k, v in self._values.items()]}


# --------------------------------------------------------------- rendering


def merge_kv_metrics(gcs) -> Dict[str, dict]:
    """Merge every producer's KV samples into one slot per metric name:
    ``{name: {type, desc, bounds, data: {tag_key: value}}}`` — counters
    summed, gauges last-writer-wins by timestamp, histogram records summed
    element-wise.  ``gcs`` is a GcsClient (or any object with
    kv_keys/kv_get taking (namespace, key))."""
    merged: Dict[str, dict] = {}
    for key in gcs.kv_keys(_NS, b""):
        raw = gcs.kv_get(_NS, key)
        if not raw:
            continue
        try:
            payload = json.loads(raw)
        except ValueError:
            continue
        name = key.decode().split("/", 1)[1]
        slot = merged.setdefault(
            name, {"type": payload["type"], "desc": payload.get("desc", ""),
                   "bounds": payload.get("bounds"), "data": {}})
        if slot["type"] != payload["type"]:
            continue
        for sample in payload["samples"]:
            tag_key = tuple(tuple(t) for t in sample[0])
            if payload["type"] == "counter":
                slot["data"][tag_key] = slot["data"].get(tag_key, 0.0) + \
                    sample[1]
            elif payload["type"] == "gauge":
                v, ts = sample[1], sample[2]
                cur = slot["data"].get(tag_key)
                if cur is None or ts >= cur[1]:
                    slot["data"][tag_key] = (v, ts)
            else:  # histogram
                rec = slot["data"].get(tag_key)
                if rec is None:
                    slot["data"][tag_key] = list(sample[1])
                else:
                    for i, v in enumerate(sample[1]):
                        rec[i] += v
    return merged


def kv_metrics_json(merged: Dict[str, dict]) -> List[dict]:
    """JSON-friendly view of ``merge_kv_metrics`` output — the dashboard's
    ``/metrics?format=json`` body (tags as dicts, histograms as
    buckets/sum/count)."""
    out: List[dict] = []
    for name, slot in sorted(merged.items()):
        series = []
        for tag_key, val in sorted(slot["data"].items()):
            tags = dict(tag_key)
            if slot["type"] == "counter":
                series.append({"tags": tags, "value": val})
            elif slot["type"] == "gauge":
                series.append({"tags": tags, "value": val[0], "ts": val[1]})
            else:
                series.append({"tags": tags, "buckets": list(val[:-2]),
                               "sum": val[-2], "count": val[-1]})
        out.append({"name": name, "type": slot["type"],
                    "desc": slot["desc"], "bounds": slot.get("bounds"),
                    "series": series})
    return out


def render_prom_lines(merged: Dict[str, dict]) -> List[str]:
    """Prometheus/OpenMetrics text lines from ``merge_kv_metrics`` output:
    # HELP / # TYPE per family, escaped label values, cumulative
    ``_bucket``/``_sum``/``_count`` expansion for histograms."""

    def esc(v: str) -> str:
        return str(v).replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")

    def labels(tag_key, extra=None) -> str:
        parts = [f'{k}="{esc(v)}"' for k, v in tag_key]
        parts.extend(extra or ())
        return "{" + ",".join(parts) + "}" if parts else ""

    lines: List[str] = []
    for name, slot in sorted(merged.items()):
        kind = {"counter": "counter", "gauge": "gauge",
                "histogram": "histogram"}[slot["type"]]
        if slot["desc"]:
            lines.append(f"# HELP {name} {slot['desc']}")
        lines.append(f"# TYPE {name} {kind}")
        for tag_key, val in sorted(slot["data"].items()):
            if slot["type"] == "counter":
                lines.append(f"{name}{labels(tag_key)} {val}")
            elif slot["type"] == "gauge":
                lines.append(f"{name}{labels(tag_key)} {val[0]}")
            else:
                bounds = slot["bounds"] or []
                cum = 0
                for i, b in enumerate(bounds):
                    cum += val[i]
                    le = 'le="%s"' % b
                    lines.append(
                        f"{name}_bucket{labels(tag_key, [le])} {cum}")
                cum += val[len(bounds)]
                inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{labels(tag_key, [inf])} {cum}")
                lines.append(f"{name}_sum{labels(tag_key)} {val[-2]}")
                lines.append(f"{name}_count{labels(tag_key)} {val[-1]}")
    return lines


def render_kv_metrics(gcs) -> List[str]:
    """Prometheus text lines for every producer's KV samples — the
    dashboard's /metrics endpoint body."""
    return render_prom_lines(merge_kv_metrics(gcs))
