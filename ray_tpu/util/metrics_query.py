"""Pure query math over metric time-series points.

The GCS metrics table (``gcs.add_metric_points``) stores timestamped DELTA
points — counters and histograms ship increments per flush interval,
gauges ship value changes (see ``metrics.collect_points``).  Everything
here is a pure function over lists of those point dicts, so the query ops
(`state.query_metrics`, ``ray_tpu metrics``, ``/api/metrics_range``) and
the alert rule engine share one implementation and the math is testable
without a cluster.

Shapes:

* point: ``{"name", "kind", "tags": [[k, v], ...], "ts", "value"}`` plus
  ``"bounds"`` for histograms (``value`` is then
  ``[bucket_deltas..., +inf_delta, sum_delta, count_delta]``).
* quantiles are computed Prometheus-style: merge the bucket deltas over
  the window, then linearly interpolate inside the target bucket — never
  by averaging per-producer percentiles (which has no meaning).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["filter_points", "rate", "sum_deltas", "merge_histogram",
           "quantile_from_buckets", "quantile_over_window", "last_value",
           "series_summary"]


def _tags_match(point_tags: Sequence, want: Optional[Dict[str, str]]) -> bool:
    if not want:
        return True
    have = {k: v for k, v in point_tags}
    return all(have.get(k) == v for k, v in want.items())


def filter_points(points: Iterable[dict], name: Optional[str] = None,
                  tags: Optional[Dict[str, str]] = None,
                  since: Optional[float] = None,
                  until: Optional[float] = None) -> List[dict]:
    """Range read: points for ``name`` whose tags contain ``tags`` and
    whose timestamp falls in ``(since, until]``, in timestamp order."""
    out = [p for p in points
           if (name is None or p["name"] == name)
           and (since is None or p["ts"] > since)
           and (until is None or p["ts"] <= until)
           and _tags_match(p.get("tags", ()), tags)]
    out.sort(key=lambda p: p["ts"])
    return out


def sum_deltas(points: Iterable[dict]) -> float:
    """Total increment across counter delta points (histogram points count
    their ``count`` increment)."""
    total = 0.0
    for p in points:
        v = p["value"]
        total += v[-1] if isinstance(v, list) else v
    return total


def rate(points: Iterable[dict], window_s: float,
         now: Optional[float] = None) -> float:
    """Per-second increase over the trailing window.  Because stored
    points are already deltas, this is a plain sum over the window divided
    by the window — no counter-reset heuristics needed."""
    if window_s <= 0:
        raise ValueError("window_s must be positive")
    pts = list(points)
    if now is None:
        now = max((p["ts"] for p in pts), default=0.0)
    windowed = [p for p in pts if now - window_s < p["ts"] <= now]
    return sum_deltas(windowed) / window_s


def last_value(points: Iterable[dict]) -> Optional[float]:
    """Latest gauge value (or counter delta) by timestamp."""
    best = None
    for p in points:
        if best is None or p["ts"] >= best["ts"]:
            best = p
    if best is None:
        return None
    v = best["value"]
    return v[-1] if isinstance(v, list) else v


def merge_histogram(points: Iterable[dict]
                    ) -> Optional[Tuple[List[float], List[float]]]:
    """Merge histogram delta points into one ``(bounds, totals)`` pair
    where ``totals`` is ``[bucket_counts..., +inf, sum, count]``.  Points
    with mismatched bounds are skipped (a redefined histogram mid-window —
    merging those buckets would be nonsense)."""
    bounds: Optional[List[float]] = None
    totals: Optional[List[float]] = None
    for p in points:
        if p.get("kind") != "histogram" or "bounds" not in p:
            continue
        if bounds is None:
            bounds = list(p["bounds"])
            totals = [0.0] * len(p["value"])
        elif list(p["bounds"]) != bounds or \
                len(p["value"]) != len(totals):
            continue
        for i, v in enumerate(p["value"]):
            totals[i] += v
    if bounds is None:
        return None
    return bounds, totals


def quantile_from_buckets(q: float, bounds: Sequence[float],
                          totals: Sequence[float]) -> Optional[float]:
    """Prometheus-style ``histogram_quantile`` over merged bucket counts
    (``totals`` = ``[per-bucket..., +inf, sum, count]``): walk the
    cumulative distribution to the target rank and interpolate linearly
    inside the containing bucket.  The +inf bucket clamps to the highest
    finite bound (nothing better is known).  Returns None on empty data."""
    if not 0.0 <= q <= 1.0:
        raise ValueError("q must be in [0, 1]")
    count = totals[-1]
    if count <= 0:
        return None
    target = q * count
    cum = 0.0
    for i, bound in enumerate(bounds):
        prev_cum = cum
        cum += totals[i]
        if cum >= target:
            lo = bounds[i - 1] if i > 0 else 0.0
            if totals[i] == 0:
                return bound
            return lo + (bound - lo) * (target - prev_cum) / totals[i]
    return float(bounds[-1]) if bounds else None


def quantile_over_window(points: Iterable[dict], q: float,
                         window_s: Optional[float] = None,
                         now: Optional[float] = None) -> Optional[float]:
    """Quantile of a histogram series over a trailing window: merge the
    window's bucket DELTAS, then take the quantile of the merged
    distribution."""
    pts = [p for p in points if p.get("kind") == "histogram"]
    if window_s is not None:
        if now is None:
            now = max((p["ts"] for p in pts), default=0.0)
        pts = [p for p in pts if now - window_s < p["ts"] <= now]
    merged = merge_histogram(pts)
    if merged is None:
        return None
    bounds, totals = merged
    return quantile_from_buckets(q, bounds, totals)


def series_summary(points: Iterable[dict], window_s: float = 60.0,
                   now: Optional[float] = None) -> List[dict]:
    """Group points into distinct ``(name, tags)`` series with activity
    stats — the backing for ``ray_tpu metrics top``.  Counter/histogram
    series report their per-second rate over the trailing window; gauges
    report their latest value."""
    groups: Dict[Tuple, List[dict]] = {}
    for p in points:
        key = (p["name"], tuple(tuple(t) for t in p.get("tags", ())))
        groups.setdefault(key, []).append(p)
    if now is None:
        now = max((p["ts"] for g in groups.values() for p in g),
                  default=0.0)
    out = []
    for (name, tags), pts in groups.items():
        kind = pts[-1].get("kind", "counter")
        row = {"name": name, "tags": [list(t) for t in tags],
               "kind": kind, "points": len(pts),
               "last_ts": max(p["ts"] for p in pts)}
        if kind == "gauge":
            row["value"] = last_value(pts)
        else:
            row["rate"] = rate(pts, window_s, now=now)
            row["total"] = sum_deltas(pts)
            if kind == "histogram":
                row["p99"] = quantile_over_window(pts, 0.99, window_s, now)
        out.append(row)
    out.sort(key=lambda r: -(r.get("rate") or 0.0))
    return out
