"""Distributed FIFO queue backed by an asyncio actor.

Reference analogue: `python/ray/util/queue.py` (``Queue`` — an actor
wrapping asyncio.Queue; blocking callers park INSIDE the actor, so a
blocked get/put costs one outstanding actor call, not a poll loop).
"""

from __future__ import annotations

from typing import Any, List, Optional

__all__ = ["Queue", "Empty", "Full"]


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    """Coroutine methods run on the actor's asyncio loop — single-threaded,
    so the queue state is race-free even with many parked callers."""

    def __init__(self, maxsize: int):
        import asyncio

        self._q: "asyncio.Queue" = asyncio.Queue(maxsize=maxsize)

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def full(self) -> bool:
        return self._q.full()

    async def put(self, item, timeout: Optional[float]) -> bool:
        """timeout None = wait forever; 0 = non-blocking."""
        import asyncio

        if timeout == 0:
            try:
                self._q.put_nowait(item)
                return True
            except asyncio.QueueFull:
                return False
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_batch(self, items: List[Any]) -> bool:
        """All-or-nothing: no partial enqueue on overflow."""
        import asyncio

        if self._q.maxsize > 0 and \
                self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for item in items:
            try:
                self._q.put_nowait(item)
            except asyncio.QueueFull:  # pragma: no cover — capacity checked
                return False
        return True

    async def get(self, timeout: Optional[float]):
        import asyncio

        if timeout == 0:
            try:
                return True, self._q.get_nowait()
            except asyncio.QueueEmpty:
                return False, None
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def get_batch(self, n: int):
        import asyncio

        out = []
        while len(out) < n:
            try:
                out.append(self._q.get_nowait())
            except asyncio.QueueEmpty:
                break
        return out


class Queue:
    """``Queue(maxsize=0)`` — unbounded by default; handles are
    serializable, so producers/consumers can live in any task or actor."""

    def __init__(self, maxsize: int = 0,
                 *, actor_options: Optional[dict] = None):
        import ray_tpu

        self.maxsize = maxsize
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        # Each PARKED blocking caller holds one concurrency slot until it
        # resolves; the default matches the reference's async-actor default
        # (1000) so realistic producer/consumer counts cannot wedge the
        # actor's dispatch queue.  Parked coroutines are cheap (one asyncio
        # task each).
        opts.setdefault("max_concurrency", 1000)
        self._actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    # ------------------------------------------------------------- inspect

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.full.remote())

    # ------------------------------------------------------------- put/get

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        import ray_tpu

        t = 0 if not block else timeout
        ok = ray_tpu.get(self._actor.put.remote(item, t))
        if not ok:
            raise Full

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        t = 0 if not block else timeout
        ok, item = ray_tpu.get(self._actor.get.remote(t))
        if not ok:
            raise Empty
        return item

    def get_nowait(self):
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]):
        import ray_tpu

        if not ray_tpu.get(self._actor.put_batch.remote(list(items))):
            raise Full(f"{len(items)} items do not fit")

    def get_nowait_batch(self, n: int) -> List[Any]:
        import ray_tpu

        return ray_tpu.get(self._actor.get_batch.remote(n))

    def shutdown(self):
        import ray_tpu

        try:
            ray_tpu.kill(self._actor)
        except Exception:  # noqa: BLE001
            pass
