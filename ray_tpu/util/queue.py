"""Distributed FIFO queue backed by an actor.

Reference analogue: `python/ray/util/queue.py` (``Queue`` — an actor
wrapping asyncio.Queue with blocking/non-blocking put/get across
processes).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

__all__ = ["Queue", "Empty", "Full"]


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        from collections import deque

        self._maxsize = maxsize
        self._items: deque = deque()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return self._maxsize > 0 and len(self._items) >= self._maxsize

    def put(self, item) -> bool:
        if self._maxsize > 0 and len(self._items) >= self._maxsize:
            return False
        self._items.append(item)
        return True

    def put_batch(self, items: List[Any]) -> int:
        n = 0
        for item in items:
            if not self.put(item):
                break
            n += 1
        return n

    def get(self):
        if not self._items:
            return False, None
        return True, self._items.popleft()

    def get_batch(self, n: int):
        out = []
        while self._items and len(out) < n:
            out.append(self._items.popleft())
        return out


class Queue:
    """``Queue(maxsize=0)`` — unbounded by default; handles are
    serializable, so producers/consumers can live in any task or actor."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        import ray_tpu

        self.maxsize = maxsize
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        opts.setdefault("max_concurrency", 8)
        self._actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    # ------------------------------------------------------------- inspect

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.full.remote())

    # ------------------------------------------------------------- put/get

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item):
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self):
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]):
        import ray_tpu

        n = ray_tpu.get(self._actor.put_batch.remote(list(items)))
        if n < len(items):
            raise Full(f"only {n}/{len(items)} items fit")

    def get_nowait_batch(self, n: int) -> List[Any]:
        import ray_tpu

        return ray_tpu.get(self._actor.get_batch.remote(n))

    def shutdown(self):
        import ray_tpu

        try:
            ray_tpu.kill(self._actor)
        except Exception:  # noqa: BLE001
            pass
