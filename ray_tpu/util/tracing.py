"""Distributed tracing: spans around task/actor submission + execution.

Reference analogue: `python/ray/util/tracing/tracing_helper.py`
(``_tracing_task_invocation :289`` wraps submission,
``_inject_tracing_into_function :322`` wraps execution, span context rides
in task metadata).  Same shape here, first-class instead of monkey-wrapped:
when tracing is enabled, ``remote()`` records a submit span and stamps a
W3C-style context (trace_id, span_id) onto the TaskSpec; the executing
worker opens a child span around the user function.

Exporter: spans append to ``$RAY_TPU_TRACE_DIR/<pid>.jsonl`` (one process,
one file — chrome://tracing and OpenTelemetry collectors both ingest
line-JSON easily).  The opentelemetry *API* package is optional and not
required; span ids use the same 128/64-bit hex format so exported spans
correlate with any surrounding otel spans.
"""

from __future__ import annotations

import contextvars
import json
import os
import secrets
import time

from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock

config.define("trace_dir", str, "",
              "Span-export directory: tracing is enabled in any process "
              "that sees this set (enable_tracing exports it so spawned "
              "workers inherit the choice).", live=True)
from typing import Any, Dict, Optional

__all__ = ["enable_tracing", "tracing_enabled", "span", "current_trace_ctx"]

_ENV = "RAY_TPU_TRACE_DIR"

_enabled = False
_trace_dir: Optional[str] = None
_file = None
_file_lock = make_lock("tracing.file")
_current: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)  # {"trace_id", "span_id"}


def enable_tracing(trace_dir: Optional[str] = None) -> str:
    """Turn tracing on for this process AND future workers (the directory
    is exported via the environment, which spawned workers inherit —
    reference: tracing startup hook).  Returns the trace dir."""
    global _enabled, _trace_dir
    trace_dir = trace_dir or config.trace_dir \
        or os.path.join(os.path.expanduser("~"), ".ray_tpu", "traces")
    os.makedirs(trace_dir, exist_ok=True)
    os.environ[_ENV] = trace_dir
    _trace_dir = trace_dir
    _enabled = True
    return trace_dir


def maybe_enable_from_env():
    """Called at worker startup: inherit the driver's tracing choice."""
    if config.trace_dir:
        enable_tracing(config.trace_dir)


def tracing_enabled() -> bool:
    return _enabled


def current_trace_ctx() -> Optional[Dict[str, str]]:
    """The active span's context, for propagation into a TaskSpec."""
    return _current.get()


def _emit(record: dict):
    global _file
    if _trace_dir is None:
        return
    with _file_lock:
        if _file is None:
            _file = open(os.path.join(_trace_dir, f"{os.getpid()}.jsonl"),
                         "a", buffering=1)
        _file.write(json.dumps(record) + "\n")


class span:
    """Context manager recording one span; nests via contextvars and
    parents across processes via an explicit ``parent`` ctx dict."""

    def __init__(self, name: str, parent: Optional[Dict[str, str]] = None,
                 **attributes: Any):
        self.name = name
        self.attributes = attributes
        explicit = parent or _current.get()
        self.trace_id = (explicit["trace_id"] if explicit
                         else secrets.token_hex(16))
        self.parent_id = explicit["span_id"] if explicit else None
        self.span_id = secrets.token_hex(8)
        self._token = None
        self._t0 = 0.0

    @property
    def ctx(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def set_error(self, message: str):
        """Mark the span failed without an exception crossing the with
        block (e.g. a task error converted into an error reply)."""
        self._error = message

    def __enter__(self) -> "span":
        self._t0 = time.time()
        self._error: Optional[str] = None
        self._token = _current.set(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb):
        _current.reset(self._token)
        if not _enabled:
            return False
        end = time.time()
        failed = exc_type is not None or self._error is not None
        _emit({
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_us": int(self._t0 * 1e6),
            "duration_us": int((end - self._t0) * 1e6),
            "pid": os.getpid(),
            "status": "ERROR" if failed else "OK",
            **({"error": repr(exc) if exc is not None else self._error}
               if failed else {}),
            "attributes": self.attributes,
        })
        return False


def submit_with_span(worker, spec, **attrs):
    """Submit a TaskSpec under a 'task.submit' span (shared by remote
    functions and actor methods); the span covers the actual submission
    and its context propagates to the executing worker via the spec."""
    if not _enabled:
        return worker.submit_spec(spec)
    with span(f"task.submit {spec.name}",
              task_id=spec.task_id.hex(), **attrs) as sp:
        spec.trace_ctx = sp.ctx
        return worker.submit_spec(spec)


def read_spans(trace_dir: Optional[str] = None,
               name_prefix: Optional[str] = None):
    """All spans recorded under the trace dir (tests/tooling).
    ``name_prefix`` filters at read time (e.g. ``"task.submit"`` — the
    timeline's flow-event feed) so callers don't materialize every
    execution span of a long run just to pick out the submits."""
    trace_dir = trace_dir or _trace_dir or config.trace_dir or None
    out = []
    if not trace_dir or not os.path.isdir(trace_dir):
        return out
    for name in sorted(os.listdir(trace_dir)):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(trace_dir, name)) as f:
            for line in f:
                try:
                    span_rec = json.loads(line)
                except ValueError:
                    continue
                if (name_prefix is None
                        or str(span_rec.get("name", ""))
                        .startswith(name_prefix)):
                    out.append(span_rec)
    return out
