"""Request-flow distributed tracing: every hop of a call spanned.

Reference analogue: `python/ray/util/tracing/tracing_helper.py`
(``_tracing_task_invocation :289`` wraps submission,
``_inject_tracing_into_function :322`` wraps execution, span context rides
in task metadata).  Grown from that two-span skeleton into a first-class
request-flow layer:

* ``remote()`` records a ``task.submit`` span and stamps a W3C-style
  context (trace_id, span_id, sampled) onto the TaskSpec; the context
  propagates through the frame protocol (local submits, ``xtask``
  forwarding, actor-call frames, Serve handle calls) so every process a
  request touches parents its spans under one trace.
* The raylet synthesizes hop spans from its task lifecycle transitions
  (inbox receipt, queue wait, dispatch, result seal), the pull manager's
  data-channel pulls, and recovery events (reconstruction, replication,
  checkpoint restore) — see ``Raylet._trace_hop``.
* The executing worker opens ``task.run`` with ``worker.get_args`` /
  ``worker.exec`` / ``worker.result_push`` children; the caller's
  ``get()`` closes the loop with a ``task.get`` wakeup span.
* Direct worker→worker calls (core/direct.py) span their two transport
  hops — ``worker.direct_send`` (caller encode + socket hand-off) and
  ``worker.direct_result`` (result receipt/demux) — under the same
  submit context, so ``trace_summary`` shows the raylet inbox/queue/
  dispatch/result hops GONE from the critical path rather than merely
  faster.  Both hops honor the unsampled fast path: sampled-out calls
  pay two dict probes, no span objects, no export traffic.

Sampling is head-based (``RAY_TPU_TRACE_SAMPLE``): the decision is made
once at the trace root, deterministically from the trace id, and rides the
context — unsampled requests cost one random id mint at submit and a dict
read per lifecycle event.  ERRORED spans are always exported regardless of
the sampling decision (`span.__exit__`), so failures are never invisible.

Export: spans append to a bounded per-process buffer (overflow drops the
oldest and counts — export backpressure never blocks the caller) and are
batch-flushed toward the cluster-wide GCS trace table: workers ship theirs
to their raylet over the control socket, raylets (which share a process
with the driver in single-node mode) drain the buffer on their task-event
cadence and post to the GCS.  The legacy per-process JSONL export under
``RAY_TPU_TRACE_DIR`` is kept for offline use, now with size-bounded
rotation.  Span ids use the 128/64-bit hex format so exported spans
correlate with any surrounding OpenTelemetry spans.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time

from ray_tpu.core.config import config
from ray_tpu.util.locks import make_lock

config.define("trace", bool, False,
              "Master tracing switch: enable_tracing() exports it so "
              "spawned workers inherit the choice even with no trace_dir "
              "(GCS-table-only export).", live=True)
config.define("trace_dir", str, "",
              "Span-export directory (optional JSONL export; "
              "enable_tracing exports it so spawned workers inherit the "
              "choice — RAY_TPU_TRACE alone decides whether tracing is "
              "on).", live=True)
config.define("trace_sample", float, 1.0,
              "Head-based sampling probability for new traces (decided "
              "deterministically from the trace id at the root, propagated "
              "in the span context).  Errored spans export regardless — "
              "failures are always visible.", live=True)
config.define("trace_export", bool, True,
              "Export spans to the cluster-wide GCS trace table "
              "(RAY_TPU_TRACE_EXPORT=0 keeps tracing file/ctx-only).",
              live=True)
config.define("trace_buffer_size", int, 4096,
              "Per-process cap on not-yet-flushed spans; overflow drops "
              "the OLDEST spans and counts them — export backpressure "
              "never blocks the traced code path.")
config.define("trace_flush_interval_s", float, 0.25,
              "Span batch-flush period (worker -> raylet -> GCS trace "
              "table).")
config.define("trace_table_max", int, 20000,
              "GCS-side trace-table cap per job: oldest spans evicted "
              "first, eviction counted in trace_table_stats.")
config.define("trace_file_max_mb", int, 64,
              "Rotation bound for the per-process JSONL trace file: at "
              "the cap the file rotates to <pid>.jsonl.1 (one rotation "
              "kept) so a long-lived traced process is disk-bounded.")

from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["enable_tracing", "tracing_enabled", "span", "maybe_span",
           "current_trace_ctx", "trace_sampled", "emit_span", "hop",
           "read_spans", "drain_pending", "flush_spans", "set_flush_target"]

_ENV = "RAY_TPU_TRACE_DIR"

_enabled = False
_trace_dir: Optional[str] = None
_file = None  # guard: _file_lock
_file_bytes = 0  # guard: _file_lock
_file_lock = make_lock("tracing.file")
_proc_label = "driver"
_job = config.job_id or "driver"
_current: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)  # {"trace_id","span_id","sampled"}

# Pending-span export buffer (bounded; see drain_pending)
_buf_lock = make_lock("tracing.buffer")
_pending: List[dict] = []  # guard: _buf_lock
_dropped = 0               # guard: _buf_lock
# Flush target: callable(spans, dropped) shipping a batch toward the GCS
# trace table (worker: control socket; client driver: TCP request).  The
# driver/raylet processes need none — the raylet drains the buffer itself
# on its flush timer.
_flush_fn: Optional[Callable[[List[dict], int], None]] = None
_flusher_started = False  # guard: _buf_lock

# get()-wakeup parenting: first return-oid (hex) of a sampled submit ->
# span ctx, so the caller's get() can parent its task.get span.  Bounded
# LRU — a fire-and-forget flood must not pin contexts forever.
from collections import OrderedDict as _OD

_get_ctx: "OrderedDict" = _OD()  # guard: _buf_lock
_GET_CTX_CAP = 8192


def enable_tracing(trace_dir: Optional[str] = None) -> Optional[str]:
    """Turn tracing on for this process AND future workers (the choice is
    exported via the environment, which spawned workers inherit —
    reference: tracing startup hook).  Idempotent: re-enabling with the
    same (or no) directory keeps the open export file and counters.
    Returns the trace dir (None when exporting to the GCS table only)."""
    global _enabled, _trace_dir
    trace_dir = trace_dir or config.trace_dir or None
    _live["at"] = -1.0  # take effect NOW, not at the 50ms cache expiry
    if _enabled and (trace_dir is None or trace_dir == _trace_dir):
        os.environ["RAY_TPU_TRACE"] = "1"  # undo a runtime kill switch
        return _trace_dir  # idempotent re-enable
    if trace_dir is not None:
        os.makedirs(trace_dir, exist_ok=True)
        # parent -> child transport: spawned workers inherit the choice
        os.environ[_ENV] = trace_dir
        if _trace_dir is not None and trace_dir != _trace_dir:
            with _file_lock:
                _close_file_locked()
        _trace_dir = trace_dir
    os.environ["RAY_TPU_TRACE"] = "1"
    _enabled = True
    return _trace_dir


def maybe_enable_from_env():
    """Called at worker startup: inherit the driver's tracing choice.
    RAY_TPU_TRACE is the authority — enable_tracing() always exports it
    alongside the dir, and honoring ONLY it means an operator's
    RAY_TPU_TRACE=0 kill switch is not silently undone in every newly
    started process just because a trace dir remains configured."""
    if config.trace:
        enable_tracing(config.trace_dir or None)


# Live-flag cache: RAY_TPU_TRACE / RAY_TPU_TRACE_SAMPLE are runtime
# toggles, but a registry read costs ~3us (env read + parse) and the
# submit/execute hot paths consult them several times per task.  Re-read
# at most every 50ms (the same cadence the chaos partition file uses):
# a toggle lands cluster-wide within one tick, and the per-call cost
# drops to a monotonic read + dict lookup.
_live = {"at": -1.0, "on": False, "sample": 1.0}


def _live_flags() -> dict:
    now = time.monotonic()
    if now - _live["at"] > 0.05:
        _live["on"] = config.trace
        _live["sample"] = config.trace_sample
        _live["at"] = now
    return _live


def tracing_enabled() -> bool:
    """Tracing is on when this process enabled it AND the live master
    switch agrees — RAY_TPU_TRACE=0 is a cluster-wide runtime kill switch
    (each process re-reads its env through the config registry, so the
    bench's interleaved on/off toggling needs no restart)."""
    return _enabled and _live_flags()["on"]


def set_process_label(label: str):
    """Span attribution for Perfetto lanes: 'driver' | 'worker' | 'raylet'
    | 'gcs' (set once at process start)."""
    global _proc_label
    _proc_label = label


def current_trace_ctx() -> Optional[Dict[str, Any]]:
    """The active span's context, for propagation into a TaskSpec."""
    return _current.get()


def trace_sampled(trace_id: str, rate: Optional[float] = None) -> bool:
    """Deterministic head-sampling decision: a pure function of the trace
    id, so every process that sees the id agrees without coordination.
    The rate is read live from config (via the 50ms flag cache — only
    trace ROOTS consult it)."""
    rate = _live_flags()["sample"] if rate is None else rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) <= int(rate * 0xFFFFFFFF)


def _close_file_locked():  # requires: _file_lock
    global _file, _file_bytes
    if _file is not None:
        try:
            _file.close()
        except OSError:
            pass
        _file = None
        _file_bytes = 0


def _write_file(line: str):
    """JSONL export with size-bounded rotation (one .1 generation kept)."""
    global _file, _file_bytes
    with _file_lock:
        if _file is None:
            path = os.path.join(_trace_dir, f"{os.getpid()}.jsonl")
            try:
                _file_bytes = os.path.getsize(path)
            except OSError:
                _file_bytes = 0
            _file = open(path, "a", buffering=1)
        cap = config.trace_file_max_mb * (1 << 20)
        if cap > 0 and _file_bytes + len(line) > cap:
            path = os.path.join(_trace_dir, f"{os.getpid()}.jsonl")
            _close_file_locked()
            try:
                os.replace(path, path + ".1")
            except OSError:
                pass  # rotation failed: keep appending, count honestly
            _file = open(path, "a", buffering=1)
            try:
                # 0 after a successful rotation; the real size when the
                # rename failed — so the cap keeps being enforced instead
                # of restarting the count against an over-cap file
                _file_bytes = os.path.getsize(path)
            except OSError:
                _file_bytes = 0
        _file.write(line)
        _file_bytes += len(line)


def _emit(record: dict):
    """Route one finished span record to the enabled exporters."""
    if not tracing_enabled():
        return
    if _trace_dir is not None:
        try:
            _write_file(json.dumps(record) + "\n")
        except (OSError, ValueError):
            pass
    if not config.trace_export:
        return
    global _dropped
    with _buf_lock:
        _pending.append(record)
        if len(_pending) > config.trace_buffer_size:
            del _pending[0]
            _dropped += 1


def drain_pending() -> Tuple[List[dict], int]:
    """Take the buffered spans + the drop count since the last drain (the
    raylet's flush timer and the worker flusher both feed from here)."""
    global _dropped
    with _buf_lock:
        if not _pending and not _dropped:
            return [], 0
        spans, dropped = list(_pending), _dropped
        _pending.clear()
        _dropped = 0
    return spans, dropped


def has_pending() -> bool:
    return bool(_pending)  # unguarded-ok: racy len probe, callers re-check


def set_flush_target(fn: Optional[Callable[[List[dict], int], None]]):
    """Register the batch shipper for processes with no in-process raylet
    (workers, TCP client drivers) and start the cadence flusher."""
    global _flush_fn, _flusher_started
    _flush_fn = fn
    if fn is None:
        return
    with _buf_lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(target=_flush_loop, name="trace-flush",
                     daemon=True).start()


def _flush_loop():
    while True:
        time.sleep(max(0.05, config.trace_flush_interval_s))  # blocking-ok: dedicated flusher thread
        try:
            flush_spans()
        except Exception:  # noqa: BLE001 — flusher must live
            pass


def flush_spans():
    """Ship buffered spans through the registered flush target now (no-op
    without one — the raylet drains the buffer directly in that case)."""
    fn = _flush_fn
    if fn is None:
        return
    spans, dropped = drain_pending()
    if spans or dropped:
        fn(spans, dropped)


# ------------------------------------------------------------------ spans


# Id minting: seeded PRNG instead of per-span urandom syscalls (same
# trick as the protocol's task-id minting) — ids only need uniqueness,
# not cryptographic strength.  One module-level instance: CPython's
# C-implemented getrandbits is a single call under the GIL (no torn
# state across threads), and a fork hook re-seeds the child so spawned
# streams can't collide with the parent's.
import random as _random

_rand = _random.Random(os.urandom(16))
if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _rand.seed(os.urandom(16)))


def _new_trace_id() -> str:
    return f"{_rand.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_rand.getrandbits(64):016x}"


class span:
    """Context manager recording one span; nests via contextvars and
    parents across processes via an explicit ``parent`` ctx dict.  The
    root span makes the head-sampling decision; children inherit it.
    Unsampled spans still mint ids and propagate context (so a later
    ERROR anywhere in the trace exports with real ids) but are not
    exported unless they fail."""

    def __init__(self, name: str, parent: Optional[Dict[str, Any]] = None,
                 **attributes: Any):
        self.name = name
        self.attributes = attributes
        explicit = parent or _current.get()
        if explicit:
            self.trace_id = explicit["trace_id"]
            self.parent_id = explicit.get("span_id")
            self.sampled = bool(explicit.get("sampled", True))
        else:
            self.trace_id = _new_trace_id()
            self.parent_id = None
            self.sampled = trace_sampled(self.trace_id)
        self.span_id = _new_span_id()
        self._token = None
        self._t0 = 0.0

    @property
    def ctx(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled}

    def set_error(self, message: str):
        """Mark the span failed without an exception crossing the with
        block (e.g. a task error converted into an error reply)."""
        self._error = message

    def __enter__(self) -> "span":
        self._t0 = time.time()
        self._error: Optional[str] = None
        self._token = _current.set(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb):
        _current.reset(self._token)
        failed = exc_type is not None or self._error is not None
        if not self.sampled and not failed:
            return False  # head-sampled out; errors always export
        if not tracing_enabled():
            return False
        end = time.time()
        _emit({
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_us": int(self._t0 * 1e6),
            "duration_us": int((end - self._t0) * 1e6),
            "pid": os.getpid(),
            "node": config.node_id[:12],
            "proc": _proc_label,
            "job": _job,
            "status": "ERROR" if failed else "OK",
            **({"error": repr(exc) if exc is not None else self._error}
               if failed else {}),
            "attributes": self.attributes,
        })
        return False


class _NullSpan:
    def __enter__(self):
        return self

    def set_error(self, message: str):
        pass

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def maybe_span(name: str, **attributes):
    """A child span when a trace context is active, else a no-op — the
    in-function instrumentation hook (worker arg resolution, GCS RPCs,
    checkpoint restore)."""
    if _current.get() is None or not tracing_enabled():
        return _NULL_SPAN
    return span(name, **attributes)


def emit_span(name: str, trace_id: str, parent_id: Optional[str],
              start: float, end: float, status: str = "OK",
              error: Optional[str] = None, proc: Optional[str] = None,
              **attributes: Any) -> str:
    """Record a span from measured timestamps (the raylet's hop spans are
    synthesized from lifecycle transition times on its single event
    thread, where contextvar nesting is meaningless).  Returns the new
    span id."""
    span_id = _new_span_id()
    _emit({
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "start_us": int(start * 1e6),
        "duration_us": max(0, int((end - start) * 1e6)),
        "pid": os.getpid(),
        "node": config.node_id[:12],
        "proc": proc or _proc_label,
        "job": _job,
        "status": status,
        **({"error": error} if error else {}),
        "attributes": attributes,
    })
    return span_id


def hop(name: str, parent: Optional[Dict[str, Any]], start: float,
        end: float, status: str = "OK", error: Optional[str] = None,
        proc: Optional[str] = None, **attributes: Any) -> Optional[str]:
    """Emit a measured hop span under ``parent`` (honoring its sampling
    bit; errored hops export regardless).  With no parent — e.g. a
    recovery event whose triggering request is unknown — a fresh root
    trace is minted and head-sampled."""
    if not tracing_enabled():
        return None
    if parent is not None:
        if not parent.get("sampled", True) and status == "OK":
            return None
        return emit_span(name, parent["trace_id"], parent.get("span_id"),
                         start, end, status=status, error=error, proc=proc,
                         **attributes)
    trace_id = _new_trace_id()
    if not trace_sampled(trace_id) and status == "OK":
        return None
    return emit_span(name, trace_id, None, start, end, status=status,
                     error=error, proc=proc, **attributes)


# ------------------------------------------------------------- submission


def submit_with_span(worker, spec, **attrs):
    """Submit a TaskSpec under a 'task.submit' span (shared by remote
    functions and actor methods); the span covers the actual submission
    and its context — including the head-sampling decision — propagates
    to every hop via the spec.

    Sampled-out requests take a fast path: the context (real ids +
    sampled=False) is stamped so a downstream ERROR can still export
    with a coherent trace, but no span object, contextvar churn, or
    export-buffer traffic happens — at RAY_TPU_TRACE_SAMPLE=0.01 the
    other 99% of submits pay only the id mint and this dict."""
    if not tracing_enabled():
        return worker.submit_spec(spec)
    parent = _current.get()
    if parent is not None:
        trace_id = parent["trace_id"]
        parent_id = parent.get("span_id")
        sampled = bool(parent.get("sampled", True))
    else:
        trace_id = _new_trace_id()
        parent_id = None
        sampled = trace_sampled(trace_id)
    if not sampled:
        spec.trace_ctx = {"trace_id": trace_id, "span_id": parent_id,
                          "sampled": False}
        return worker.submit_spec(spec)
    with span(f"task.submit {spec.name}",
              parent={"trace_id": trace_id, "span_id": parent_id,
                      "sampled": True},
              task_id=spec.task_id.hex(), **attrs) as sp:
        spec.trace_ctx = sp.ctx
        refs = worker.submit_spec(spec)
    if refs:
        with _buf_lock:
            _get_ctx[refs[0].hex()] = sp.ctx
            while len(_get_ctx) > _GET_CTX_CAP:
                _get_ctx.popitem(last=False)
    return refs


def lookup_get_ctx(refs) -> Optional[Dict[str, Any]]:
    """Span context of the submit that produced one of ``refs`` (first
    match wins, entry consumed) — parents the caller's task.get span."""
    if not tracing_enabled():
        return None
    with _buf_lock:
        for r in refs:
            ctx = _get_ctx.pop(r.hex(), None)
            if ctx is not None:
                return ctx
    return None


# ------------------------------------------------------------------ files


def read_spans(trace_dir: Optional[str] = None,
               name_prefix: Optional[str] = None):
    """All spans recorded under the trace dir (tests/tooling), including
    rotated ``.jsonl.1`` generations.  ``name_prefix`` filters at read
    time (e.g. ``"task.submit"`` — the timeline's flow-event feed) so
    callers don't materialize every execution span of a long run just to
    pick out the submits."""
    trace_dir = trace_dir or _trace_dir or config.trace_dir or None
    out = []
    if not trace_dir or not os.path.isdir(trace_dir):
        return out
    for name in sorted(os.listdir(trace_dir)):
        if not (name.endswith(".jsonl") or name.endswith(".jsonl.1")):
            continue
        with open(os.path.join(trace_dir, name)) as f:
            for line in f:
                try:
                    span_rec = json.loads(line)
                except ValueError:
                    continue
                if (name_prefix is None
                        or str(span_rec.get("name", ""))
                        .startswith(name_prefix)):
                    out.append(span_rec)
    return out
