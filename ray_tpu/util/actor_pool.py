"""ActorPool — round-robin work distribution over a fixed set of actors.

Reference analogue: `python/ray/util/actor_pool.py` (``ActorPool.map``,
``map_unordered``, ``submit``/``get_next``).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

__all__ = ["ActorPool"]


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool needs at least one actor")
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending_order: List[Any] = []  # refs in submission order

    def has_free(self) -> bool:
        return bool(self._idle)

    def submit(self, fn: Callable[[Any, Any], Any], value: Any):
        """``fn(actor, value) -> ObjectRef`` — e.g.
        ``pool.submit(lambda a, v: a.double.remote(v), 1)``."""
        if not self._idle:
            raise RuntimeError("no idle actor; call get_next() first")
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor
        self._pending_order.append(ref)
        return ref

    def has_next(self) -> bool:
        return bool(self._pending_order)

    def _recycle(self, ref):
        actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)

    def get_next(self, timeout: float = None):
        """Next result in SUBMISSION order.  On timeout the pending ref is
        kept (retry get_next later); the actor stays busy."""
        import ray_tpu

        if not self._pending_order:
            raise StopIteration
        ref = self._pending_order[0]
        try:
            result = ray_tpu.get(ref, timeout=timeout)
        except TimeoutError:
            raise  # still running: keep the ref pending, actor stays busy
        except Exception:
            # the task FAILED: it is finished, so free the actor
            self._pending_order.pop(0)
            self._recycle(ref)
            raise
        self._pending_order.pop(0)
        self._recycle(ref)
        return result

    def get_next_unordered(self, timeout: float = None):
        """Next COMPLETED result, whichever actor finishes first."""
        import ray_tpu

        if not self._pending_order:
            raise StopIteration
        ready, _ = ray_tpu.wait(list(self._pending_order), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("no task completed within timeout")
        ref = ready[0]
        self._pending_order.remove(ref)
        try:
            return ray_tpu.get(ref)
        finally:
            self._recycle(ref)

    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        """Ordered results; saturates the pool, yields lazily."""
        values = list(values)
        i = 0
        while i < len(values) and self.has_free():
            self.submit(fn, values[i])
            i += 1
        while self.has_next():
            yield self.get_next()
            if i < len(values):
                self.submit(fn, values[i])
                i += 1

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        values = list(values)
        i = 0
        while i < len(values) and self.has_free():
            self.submit(fn, values[i])
            i += 1
        while self.has_next():
            yield self.get_next_unordered()
            if i < len(values):
                self.submit(fn, values[i])
                i += 1
