"""State API — queryable cluster state.

Reference analogue: `python/ray/util/state/api.py` (``list_actors`` `:782`,
``list_nodes`` `:874`, ``list_tasks`` `:1009`, ``list_objects`` `:1054`,
``summarize_tasks`` `:1367`) over the dashboard's StateAggregator.  Sources:
the GCS tables — nodes/actors AND, since the task-event export landed, the
cluster-wide task-event table (every raylet batch-flushes its task
lifecycle events there) — plus the connected raylet's snapshot for
node-local object detail.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


def _worker():
    from ray_tpu.core.worker import global_worker

    return global_worker()


def _snapshot(objects_limit: int = 0) -> dict:
    w = _worker()
    if w.mode == "driver":
        return w.raylet.call(w.raylet.state_snapshot, objects_limit).result()
    if w.mode == "local":
        return {"node_id": "local", "tasks": [], "actors": [],
                "objects": {"num": 0, "items": []}, "events": [],
                "resources_total": {}, "resources_available": {}}
    return w._request("state_snapshot", objects_limit=objects_limit)


def _task_table_call(op: str, **kw):
    """Query the GCS task-event table cluster-wide.  The connected raylet's
    export buffer is flushed first so just-finished local tasks are visible;
    remote raylets flush on their own cadence (poll for their tail)."""
    w = _worker()
    if w.mode == "local":
        return None
    if w.mode == "driver":
        w.raylet.call(w.raylet.flush_task_events).result()
        return getattr(w.raylet.gcs, op)(**kw)
    # worker / client modes: the raylet flushes locally and proxies the op
    return w._request(op, **kw)


def _trace_table_call(op: str, **kw):
    """Query the GCS trace-span table cluster-wide.  This process's span
    buffer and the connected raylet's export buffer are flushed first so
    the freshest local spans count; remote raylets flush on their own
    cadence (poll for their tail)."""
    w = _worker()
    if w.mode == "local":
        return None
    from ray_tpu.util import tracing as _tracing

    if w.mode == "driver":
        # driver + raylet share a process: the raylet drains the shared
        # span buffer itself
        w.raylet.call(w.raylet.flush_trace_spans).result()
        return getattr(w.raylet.gcs, op)(**kw)
    # worker / client modes: ship this process's buffer to the raylet,
    # which flushes locally and proxies the read
    _tracing.flush_spans()
    return w._request(op, **kw)


def list_trace_spans(job_id: Optional[str] = None,
                     limit: int = 10000) -> List[Dict[str, Any]]:
    """The most recent retained span records, cluster-wide (GCS trace
    table, start-time ordered)."""
    return list(_trace_table_call("list_trace_spans", job_id=job_id,
                                  limit=limit) or [])


def get_trace(trace_id: str) -> Dict[str, Any]:
    """Reassemble one request's cross-process span tree plus its latency
    waterfall: ``{"trace_id", "spans", "tree", "critical_path"}`` —
    ``tree`` nests children under parents across every process the
    request touched; ``critical_path`` is the per-hop attribution (see
    ``util.trace_analysis``)."""
    from ray_tpu.util import trace_analysis

    spans = list(_trace_table_call("get_trace", trace_id=trace_id) or [])
    return {
        "trace_id": trace_id,
        "spans": spans,
        "tree": trace_analysis.build_tree(spans),
        "critical_path": trace_analysis.critical_path(spans),
    }


def trace_summary(job_id: Optional[str] = None,
                  limit: int = 100000) -> Dict[str, Any]:
    """The "where do the microseconds go" table: per-hop p50/p95/total
    attributed self-time aggregated over every retained trace, plus the
    trace-table accounting (span/trace counts, drop counter)."""
    from ray_tpu.util import trace_analysis

    spans = list(_trace_table_call("list_trace_spans", job_id=job_id,
                                   limit=limit) or [])
    out = trace_analysis.aggregate(spans)
    out["table"] = dict(_trace_table_call("trace_table_stats") or {})
    return out


def export_trace(filename: str, trace_id: Optional[str] = None,
                 job_id: Optional[str] = None, limit: int = 100000) -> int:
    """Write retained spans (one trace, or everything) as
    Perfetto/chrome://tracing JSON.  Returns the event count."""
    import json as _json

    from ray_tpu.util import trace_analysis

    if trace_id is not None:
        spans = list(_trace_table_call("get_trace", trace_id=trace_id)
                     or [])
    else:
        spans = list(_trace_table_call("list_trace_spans", job_id=job_id,
                                       limit=limit) or [])
    doc = trace_analysis.to_chrome_trace(spans)
    with open(filename, "w") as f:
        _json.dump(doc, f)
    return len(doc["traceEvents"])


def list_nodes() -> List[Dict[str, Any]]:
    """Cluster membership with resources (GCS node table)."""
    w = _worker()
    return [
        {
            "node_id": n["node_id"],
            "state": ("DEAD" if not n.get("alive", True)
                      else "DRAINING" if n.get("draining")
                      else "SUSPECT" if n.get("suspect")
                      else "ALIVE"),
            "incarnation": n.get("incarnation", 0),
            "address": n.get("address"),
            "hostname": n.get("hostname", ""),
            "resources_total": n.get("resources_total", {}),
            "resources_available": n.get("resources_available", {}),
        }
        for n in w.gcs_nodes()
    ]


def list_actors(state: Optional[str] = None) -> List[Dict[str, Any]]:
    """Cluster-wide actor table (GCS) merged with the local raylet's
    richer per-actor detail when available."""
    w = _worker()
    local = {a["actor_id"]: a for a in _snapshot().get("actors", [])}
    if w.mode == "driver":
        gcs_actors = w.raylet.gcs.list_actors()
    elif w.mode == "client":
        gcs_actors = w.gcs.list_actors()
    elif w.mode == "worker":
        gcs_actors = w._request("gcs_list_actors")
    else:
        gcs_actors = []
    out = {}
    for a in gcs_actors:
        out[a["actor_id"]] = {
            "actor_id": a["actor_id"],
            "state": a.get("state", "?").upper(),
            "name": a.get("name"),
            "owner_node": a.get("owner_node"),
            "node_id": a.get("exec_node") or a.get("owner_node"),
        }
    for aid, a in local.items():
        entry = out.setdefault(aid, {"actor_id": aid})
        entry.update({
            "state": a["state"].upper(),
            "name": a.get("name"),
            "pid": a.get("pid"),
        })
    results = list(out.values())
    if state is not None:
        results = [a for a in results if a.get("state") == state.upper()]
    return results


def list_tasks(state: Optional[str] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Cluster-wide task table: latest known state per task from the GCS
    task-event table (reference: ``list_tasks``, `api.py:1009`), including
    tasks executed on OTHER nodes."""
    rows = _task_table_call("list_task_events", state=state, limit=limit)
    return list(rows or [])


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    """Object metadata known to the connected raylet.  Routed through the
    raylet-thread ``state_snapshot`` (never reads ``_objects`` off-thread)
    with ``limit`` applied at the source, before materializing."""
    snap = _snapshot(objects_limit=max(1, limit))
    return list(snap.get("objects", {}).get("items") or [])


def summarize_tasks() -> Dict[str, int]:
    """State -> count, cluster-wide (reference: ``summarize_tasks``,
    `api.py:1367`)."""
    summary = _task_table_call("summarize_task_events")
    return dict((summary or {}).get("by_state", {}))


def task_events_summary() -> Dict[str, Any]:
    """Full task-event accounting: state counts, distinct reporting nodes,
    and the cluster-wide export drop counter (ring-buffer backpressure)."""
    return dict(_task_table_call("summarize_task_events") or {})


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects(limit=100000)
    by_status: Dict[str, int] = {}
    for o in objs:
        by_status[o["status"]] = by_status.get(o["status"], 0) + 1
    return {"total": len(objs), "by_status": by_status,
            "bytes_known": sum(o.get("size", 0) for o in objs)}


# --------------------------------------------------------------- timeline


def build_timeline(events: List[dict], spans: Optional[List[dict]] = None,
                   now: Optional[float] = None) -> List[dict]:
    """chrome://tracing trace from raw task events (and, when tracing is
    on, driver-side submit spans).

    Per task attempt, TWO sub-slices make queue wait visible next to run
    time: ``queue_wait`` (QUEUED/PENDING_ARGS -> dispatch) and ``run``
    (dispatch -> terminal).  Still-in-flight tasks get an OPEN-ENDED slice
    ending at ``now`` instead of being silently dropped, and tasks that
    fail before dispatch close their queue slice at the failure — nothing
    leaks (reference: ``ray.timeline``, `python/ray/_private/state.py:416`).
    Submit spans become flow arrows (``s``/``f``) from the submitting
    process to the first run slice of the task.
    """
    now = time.time() if now is None else now
    per_task: Dict[str, List[dict]] = {}
    for ev in sorted(events, key=lambda e: e.get("time", 0.0)):
        per_task.setdefault(ev["task_id"], []).append(ev)

    trace: List[dict] = []
    first_run: Dict[str, dict] = {}  # task_id -> first run slice (flow tgt)

    def emit(name, phase, t0, t1, pid, tid_hex, **args):
        sl = {
            "cat": "task", "name": name, "ph": "X",
            "ts": t0 * 1e6, "dur": max(0.0, (t1 - t0)) * 1e6,
            "pid": pid, "tid": pid,
            "args": {"phase": phase, "task_id": tid_hex, **args},
        }
        trace.append(sl)
        return sl

    for tid, evs in per_task.items():
        name = next((e.get("name") for e in evs if e.get("name")), tid[:8])
        queued_t: Optional[float] = None
        run_t: Optional[float] = None
        pid = 0
        node = evs[-1].get("node_id", "")
        # task events <-> traces: a sampled request's timeline slices
        # carry its trace id, so a slow slice jumps to its waterfall
        trace_id = next((e["trace_id"] for e in evs
                         if e.get("trace_id")), None)
        targs = {"trace_id": trace_id} if trace_id else {}
        for ev in evs:
            st = ev.get("state")
            t = ev.get("time", 0.0)
            if st in ("PENDING_ARGS", "QUEUED", "PENDING"):
                if queued_t is None:
                    queued_t = t
            elif st in ("RUNNING", "DISPATCHED"):
                if run_t is None:
                    run_t = t
                    pid = ev.get("pid") or 0
                    if queued_t is not None:
                        emit(name, "queue_wait", queued_t, t, pid, tid,
                             node_id=ev.get("node_id", node), **targs)
                        queued_t = None
            elif st in ("FINISHED", "FAILED", "OOM_KILLED"):
                start = run_t if run_t is not None else t
                sl = emit(name, "run", start, t, pid, tid, state=st,
                          node_id=ev.get("node_id", node), **targs,
                          **({"error": ev["error"]} if ev.get("error")
                             else {}))
                first_run.setdefault(tid, sl)
                run_t = queued_t = None
            elif st in ("RETRYING", "REQUEUED", "SPILLED", "FORWARDED",
                        "RECONSTRUCTING"):
                # attempt boundary: close whatever phase was open here
                if run_t is not None:
                    sl = emit(name, "run", run_t, t, pid, tid, state=st,
                              node_id=ev.get("node_id", node), **targs)
                    first_run.setdefault(tid, sl)
                elif queued_t is not None:
                    emit(name, "queue_wait", queued_t, t, pid, tid, state=st,
                         node_id=ev.get("node_id", node), **targs)
                run_t = queued_t = None
        # in-flight work: open-ended slices up to `now` (never dropped)
        if run_t is not None:
            sl = emit(name, "run", run_t, now, pid, tid, state="RUNNING",
                      in_flight=True, node_id=node, **targs)
            first_run.setdefault(tid, sl)
        elif queued_t is not None:
            emit(name, "queue_wait", queued_t, now, pid, tid,
                 in_flight=True, node_id=node, **targs)

    # flow arrows from submit spans (tracing on): submitting process ->
    # the task's first run slice
    for sp in spans or []:
        tid = (sp.get("attributes") or {}).get("task_id")
        if not tid or not str(sp.get("name", "")).startswith("task.submit"):
            continue
        t0 = sp.get("start_us", 0) / 1e6
        t1 = t0 + sp.get("duration_us", 0) / 1e6
        spid = sp.get("pid", 0)
        trace.append({"cat": "submit", "name": sp["name"], "ph": "X",
                      "ts": t0 * 1e6,
                      "dur": sp.get("duration_us", 0), "pid": spid,
                      "tid": spid, "args": {"task_id": tid}})
        target = first_run.get(tid)
        if target is None:
            continue
        trace.append({"cat": "flow", "name": "submit", "ph": "s",
                      "id": tid, "ts": t1 * 1e6, "pid": spid, "tid": spid})
        trace.append({"cat": "flow", "name": "submit", "ph": "f",
                      "bp": "e", "id": tid, "ts": target["ts"],
                      "pid": target["pid"], "tid": target["tid"]})
    return trace


def raw_task_events(limit: int = 100000) -> List[dict]:
    """The cluster-wide raw event log (every recorded transition)."""
    return list(_task_table_call("task_events_raw", limit=limit) or [])
