"""State API — queryable cluster state.

Reference analogue: `python/ray/util/state/api.py` (``list_actors`` `:782`,
``list_nodes`` `:874`, ``list_tasks`` `:1009`, ``list_objects`` `:1054`,
``summarize_tasks`` `:1367`) over the dashboard's StateAggregator.  Sources:
the GCS tables — nodes/actors AND, since the task-event export landed, the
cluster-wide task-event table (every raylet batch-flushes its task
lifecycle events there) — plus the connected raylet's snapshot for
node-local object detail.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


def _worker():
    from ray_tpu.core.worker import global_worker

    return global_worker()


def _snapshot(objects_limit: int = 0) -> dict:
    w = _worker()
    if w.mode == "driver":
        return w.raylet.call(w.raylet.state_snapshot, objects_limit).result()
    if w.mode == "local":
        return {"node_id": "local", "tasks": [], "actors": [],
                "objects": {"num": 0, "items": []}, "events": [],
                "resources_total": {}, "resources_available": {}}
    return w._request("state_snapshot", objects_limit=objects_limit)


def _task_table_call(op: str, **kw):
    """Query the GCS task-event table cluster-wide.  The connected raylet's
    export buffer is flushed first so just-finished local tasks are visible;
    remote raylets flush on their own cadence (poll for their tail)."""
    w = _worker()
    if w.mode == "local":
        return None
    if w.mode == "driver":
        w.raylet.call(w.raylet.flush_task_events).result()
        return getattr(w.raylet.gcs, op)(**kw)
    # worker / client modes: the raylet flushes locally and proxies the op
    return w._request(op, **kw)


def _trace_table_call(op: str, **kw):
    """Query the GCS trace-span table cluster-wide.  This process's span
    buffer and the connected raylet's export buffer are flushed first so
    the freshest local spans count; remote raylets flush on their own
    cadence (poll for their tail)."""
    w = _worker()
    if w.mode == "local":
        return None
    from ray_tpu.util import tracing as _tracing

    if w.mode == "driver":
        # driver + raylet share a process: the raylet drains the shared
        # span buffer itself
        w.raylet.call(w.raylet.flush_trace_spans).result()
        return getattr(w.raylet.gcs, op)(**kw)
    # worker / client modes: ship this process's buffer to the raylet,
    # which flushes locally and proxies the read
    _tracing.flush_spans()
    return w._request(op, **kw)


def _gcs_call(op: str, **kw):
    """Call a GCS op that must NOT be proxied synchronously through the
    raylet event thread (cluster-wide gathers like ``collect_stacks``
    push a ``node_query`` back at every raylet — a blocking proxy would
    deadlock against our own node's share).  Driver and client modes hold
    a GCS handle and call it from this thread; worker mode bounces off
    the raylet, which runs the gather on a throwaway thread."""
    w = _worker()
    if w.mode == "driver":
        return getattr(w.raylet.gcs, op)(**kw)
    if w.mode == "client":
        return getattr(w.gcs, op)(**kw)
    return None


def _profile_table_call(op: str, **kw):
    """Query the GCS profile table cluster-wide.  This process's sample
    window and the connected raylet's export buffer are flushed first so
    the freshest local samples count; remote raylets flush on their own
    cadence (``profile_flush_interval_s``)."""
    w = _worker()
    if w.mode == "local":
        return None
    from ray_tpu.util import profiling as _profiling

    if w.mode == "driver":
        # driver + raylet share a process: the raylet drains the shared
        # sampler window itself
        w.raylet.call(w.raylet.flush_profile_samples).result()
        if op == "flush_profile_samples":
            return None
        return getattr(w.raylet.gcs, op)(**kw)
    # worker / client modes: ship this process's window to the raylet,
    # which flushes locally and proxies the read
    _profiling.flush_samples()
    return w._request(op, **kw)


def _metrics_table_call(op: str, **kw):
    """Query the GCS metrics time-series table cluster-wide.  This
    process's point ring and the connected raylet's export buffer are
    flushed first so the freshest local deltas count; remote raylets
    flush on their own cadence (``internal_metrics_interval_s``)."""
    w = _worker()
    if w.mode == "local":
        return None
    from ray_tpu.util import metrics as _metrics

    if w.mode == "driver":
        # driver + raylet share a process: record this process's deltas
        # into the shared ring, which the raylet's flush drains itself
        _metrics.record_points()
        w.raylet.call(w.raylet.flush_metric_points).result()
        if op == "flush_metric_points":
            return None
        if "query_op" in kw:
            kw["op"] = kw.pop("query_op")
        return getattr(w.raylet.gcs, op)(**kw)
    # worker / client modes: ship this process's ring to the raylet,
    # which flushes locally and proxies the read.  The query kind rides
    # as query_op (the request frame's own "op" key is the table op);
    # the raylet proxy maps it back.
    _metrics.flush_points()
    return w._request(op, **kw)


# ----------------------------------------------------- metrics & alerts


def query_metrics(name: Optional[str] = None, op: str = "range",
                  tags: Optional[Dict[str, str]] = None,
                  node_id: Optional[str] = None,
                  since: Optional[float] = None,
                  until: Optional[float] = None,
                  window_s: float = 60.0, q: float = 0.99,
                  limit: int = 2000) -> Optional[Dict[str, Any]]:
    """Query the cluster metrics time-series table (timestamped DELTA
    points shipped by every node on its flush cadence).

    ``op``: ``range`` (the points), ``rate`` (per-second increase over
    the trailing ``window_s``), ``quantile`` (histogram quantile ``q``
    over the window — bucket deltas merged, never averaged percentiles),
    or ``series`` (per-series activity summary).  Returns None in local
    mode (no cluster, no table)."""
    return _metrics_table_call("query_metrics", name=name, query_op=op,
                               tags=tags, node_id=node_id, since=since,
                               until=until, window_s=window_s, q=q,
                               limit=limit)


def metrics_table_stats() -> Optional[Dict[str, Any]]:
    """Size/eviction accounting for the GCS metrics time-series table."""
    return _metrics_table_call("metrics_table_stats")


def list_alerts(state: Optional[str] = None,
                limit: int = 100) -> Optional[Dict[str, Any]]:
    """The alert table: currently-firing alerts plus the recent
    firing/resolved transition log (``state`` filters the log)."""
    w = _worker()
    if w.mode == "local":
        return None
    if w.mode == "driver":
        return w.raylet.gcs.list_alerts(state=state, limit=limit)
    return w._request("list_alerts", state=state, limit=limit)


# ------------------------------------------------------------- profiling


def list_stacks(target: Optional[str] = None,
                timeout_s: float = 3.0) -> Dict[str, Any]:
    """Live all-thread stacks from every process the target matches — the
    ``ray stack`` analogue, served by the processes themselves over the
    protocol (no external tracer, works on remote nodes).

    ``target``: ``None`` for the whole cluster (plus the GCS process), a
    node-id prefix for one node, or an actor name / actor-id prefix for
    exactly that actor's worker process.  Returns ``{"nodes": {node_id:
    [{"pid", "proc", "threads": [...]}, ...]}, "missing": [...]}`` —
    ``missing`` nodes didn't answer inside the timeout."""
    w = _worker()
    if w.mode == "local":
        return {"nodes": {}, "missing": []}
    node_id, actor_id = None, None
    if target is not None:
        nodes = [n["node_id"] for n in list_nodes()
                 if n["node_id"].startswith(target)]
        if nodes:
            node_id = target
        else:
            for a in list_actors():
                if (a["actor_id"].startswith(target)
                        or a.get("name") == target):
                    actor_id, node_id = a["actor_id"], a.get("node_id")
                    break
            if actor_id is None:
                raise ValueError(
                    f"stack target {target!r} matches no alive node id "
                    "prefix, actor id prefix, or actor name")
    kw = dict(node_id=node_id, timeout_s=timeout_s)
    if w.mode == "worker":
        out = w._request("collect_stacks", **kw)
    else:
        out = _gcs_call("collect_stacks", **kw)
    out = dict(out or {})
    if actor_id is not None:
        # keep only the matched actor's worker process (the raylet tags
        # each worker dump with its hosting actor id)
        out["nodes"] = {
            nid: [p for p in procs or []
                  if p.get("actor_id") == actor_id]
            for nid, procs in out.get("nodes", {}).items()
        }
        out["nodes"] = {nid: procs
                        for nid, procs in out["nodes"].items() if procs}
        out.pop("gcs", None)
    return out


def list_profile_samples(node_id: Optional[str] = None, since: float = 0.0,
                         limit: int = 100000) -> List[Dict[str, Any]]:
    """Retained folded stack-sample records (GCS profile table), oldest
    first — every process samples its threads at RAY_TPU_PROFILE_HZ and
    batch-flushes here (see ``util.profiling``)."""
    return list(_profile_table_call("list_profile_samples",
                                    node_id=node_id, since=since,
                                    limit=limit) or [])


def profile(duration_s: float = 2.0,
            node_id: Optional[str] = None) -> Dict[str, Any]:
    """Timed capture from the always-on samplers: wait out ``duration_s``
    (plus the flush cadence, so every node's window lands in the GCS
    table), then return the samples whose windows overlap the capture —
    with per-record task/trace/actor attribution — plus ready-to-load
    speedscope and collapsed-format exports.  Requires RAY_TPU_PROFILE=1
    (the default); with the kill switch thrown the capture comes back
    empty."""
    from ray_tpu.core.config import config as _config
    from ray_tpu.util import profiling as _profiling

    t0 = time.time()
    end = t0 + max(0.0, duration_s)
    time.sleep(max(0.0, duration_s))
    # stragglers: worker flushers tick every profile_flush_interval_s,
    # then each raylet posts on its own recurring tick — wait out both
    time.sleep(2.0 * _config.profile_flush_interval_s + 0.3)
    _profile_table_call("flush_profile_samples")
    samples = [rec for rec in list_profile_samples(node_id=node_id,
                                                   since=t0)
               if rec.get("t0", 0.0) <= end]
    return {
        "duration_s": duration_s,
        "t0": t0,
        "samples": samples,
        "num_samples": sum(int(r.get("count", 0)) for r in samples),
        "summary": _profiling.summarize(samples),
        "speedscope": _profiling.to_speedscope(
            samples, name=f"ray_tpu profile ({duration_s:.1f}s)"),
        "collapsed": _profiling.to_collapsed(samples),
    }


def profile_summary(node_id: Optional[str] = None, since: float = 0.0,
                    limit: int = 100000, top: int = 30) -> Dict[str, Any]:
    """The "where does the CPU go" table over the retained continuous
    profile: per-function self/inclusive sample counts and shares, per
    process kind, plus the profile-table accounting."""
    from ray_tpu.util import profiling as _profiling

    samples = list_profile_samples(node_id=node_id, since=since,
                                   limit=limit)
    out = _profiling.summarize(samples, top=top)
    out["table"] = dict(_profile_table_call("profile_table_stats") or {})
    return out


def export_profile(filename: str, fmt: str = "speedscope",
                   node_id: Optional[str] = None, since: float = 0.0,
                   limit: int = 100000) -> int:
    """Write retained profile samples as a speedscope JSON document
    (https://speedscope.app) or flamegraph.pl collapsed text.  Returns
    the number of sample records exported."""
    import json as _json

    from ray_tpu.util import profiling as _profiling

    samples = list_profile_samples(node_id=node_id, since=since,
                                   limit=limit)
    if fmt == "speedscope":
        with open(filename, "w") as f:
            _json.dump(_profiling.to_speedscope(samples), f)
    elif fmt == "collapsed":
        with open(filename, "w") as f:
            f.write(_profiling.to_collapsed(samples))
    else:
        raise ValueError(f"unknown profile export format {fmt!r} "
                         "(speedscope | collapsed)")
    return len(samples)


# ------------------------------------------------------------------ logs


def _logs_query(node_id: Optional[str], payload: dict,
                timeout_s: float) -> Dict[str, Any]:
    w = _worker()
    if w.mode == "local":
        return {"reports": {}, "missing": []}
    kw = dict(node_id=node_id, kind="logs", payload=payload,
              timeout_s=timeout_s)
    if w.mode == "worker":
        return dict(w._request("gcs_node_query", **kw) or {})
    return dict(_gcs_call("node_query", **kw) or {})


def list_logs(node_id: Optional[str] = None,
              timeout_s: float = 3.0) -> Dict[str, List[dict]]:
    """Per-worker log files under each node's ``session_dir/logs``
    (cluster mode), as ``{node_id: [{"name", "size", "mtime", "pid"}]}``
    — the ``ray logs`` listing, served by each raylet over the
    protocol."""
    out = _logs_query(node_id, {"action": "list"}, timeout_s)
    return {nid: rep for nid, rep in out.get("reports", {}).items()
            if isinstance(rep, list)}


def tail_log(name: str, node_id: Optional[str] = None,
             offset: Optional[int] = None, lines: int = 100,
             timeout_s: float = 3.0) -> Optional[Dict[str, Any]]:
    """One read of a worker log file: the last ``lines`` lines (or, with
    ``offset``, everything after it — feed the returned ``offset`` back
    to poll/follow).  With no ``node_id`` the first node holding the file
    answers."""
    out = _logs_query(node_id, {"action": "tail", "name": name,
                                "offset": offset, "lines": lines},
                      timeout_s)
    hits = [rep for _nid, rep in sorted(out.get("reports", {}).items())
            if isinstance(rep, dict) and "data" in rep]
    if not hits:
        return None
    if len(hits) > 1:
        # worker log names are per-raylet sequences (worker-00001.log
        # exists on EVERY node): never silently serve the wrong node's
        # file — flag the ambiguity so callers can re-ask with node_id
        hits[0]["ambiguous_nodes"] = [rep["node_id"] for rep in hits]
    return hits[0]


def list_trace_spans(job_id: Optional[str] = None,
                     limit: int = 10000) -> List[Dict[str, Any]]:
    """The most recent retained span records, cluster-wide (GCS trace
    table, start-time ordered)."""
    return list(_trace_table_call("list_trace_spans", job_id=job_id,
                                  limit=limit) or [])


def get_trace(trace_id: str) -> Dict[str, Any]:
    """Reassemble one request's cross-process span tree plus its latency
    waterfall: ``{"trace_id", "spans", "tree", "critical_path"}`` —
    ``tree`` nests children under parents across every process the
    request touched; ``critical_path`` is the per-hop attribution (see
    ``util.trace_analysis``)."""
    from ray_tpu.util import trace_analysis

    spans = list(_trace_table_call("get_trace", trace_id=trace_id) or [])
    return {
        "trace_id": trace_id,
        "spans": spans,
        "tree": trace_analysis.build_tree(spans),
        "critical_path": trace_analysis.critical_path(spans),
    }


def trace_summary(job_id: Optional[str] = None,
                  limit: int = 100000) -> Dict[str, Any]:
    """The "where do the microseconds go" table: per-hop p50/p95/total
    attributed self-time aggregated over every retained trace, plus the
    trace-table accounting (span/trace counts, drop counter)."""
    from ray_tpu.util import trace_analysis

    spans = list(_trace_table_call("list_trace_spans", job_id=job_id,
                                   limit=limit) or [])
    out = trace_analysis.aggregate(spans)
    out["table"] = dict(_trace_table_call("trace_table_stats") or {})
    return out


def export_trace(filename: str, trace_id: Optional[str] = None,
                 job_id: Optional[str] = None, limit: int = 100000) -> int:
    """Write retained spans (one trace, or everything) as
    Perfetto/chrome://tracing JSON.  Returns the event count."""
    import json as _json

    from ray_tpu.util import trace_analysis

    if trace_id is not None:
        spans = list(_trace_table_call("get_trace", trace_id=trace_id)
                     or [])
    else:
        spans = list(_trace_table_call("list_trace_spans", job_id=job_id,
                                       limit=limit) or [])
    doc = trace_analysis.to_chrome_trace(spans)
    with open(filename, "w") as f:
        _json.dump(doc, f)
    return len(doc["traceEvents"])


def list_nodes() -> List[Dict[str, Any]]:
    """Cluster membership with resources (GCS node table)."""
    w = _worker()
    return [
        {
            "node_id": n["node_id"],
            "state": ("DEAD" if not n.get("alive", True)
                      else "DRAINING" if n.get("draining")
                      else "SUSPECT" if n.get("suspect")
                      else "ALIVE"),
            "incarnation": n.get("incarnation", 0),
            "address": n.get("address"),
            "hostname": n.get("hostname", ""),
            "resources_total": n.get("resources_total", {}),
            "resources_available": n.get("resources_available", {}),
        }
        for n in w.gcs_nodes()
    ]


def list_actors(state: Optional[str] = None) -> List[Dict[str, Any]]:
    """Cluster-wide actor table (GCS) merged with the local raylet's
    richer per-actor detail when available."""
    w = _worker()
    local = {a["actor_id"]: a for a in _snapshot().get("actors", [])}
    if w.mode == "driver":
        gcs_actors = w.raylet.gcs.list_actors()
    elif w.mode == "client":
        gcs_actors = w.gcs.list_actors()
    elif w.mode == "worker":
        gcs_actors = w._request("gcs_list_actors")
    else:
        gcs_actors = []
    out = {}
    for a in gcs_actors:
        out[a["actor_id"]] = {
            "actor_id": a["actor_id"],
            "state": a.get("state", "?").upper(),
            "name": a.get("name"),
            "owner_node": a.get("owner_node"),
            "node_id": a.get("exec_node") or a.get("owner_node"),
        }
    for aid, a in local.items():
        entry = out.setdefault(aid, {"actor_id": aid})
        entry.update({
            "state": a["state"].upper(),
            "name": a.get("name"),
            "pid": a.get("pid"),
        })
    results = list(out.values())
    if state is not None:
        results = [a for a in results if a.get("state") == state.upper()]
    return results


def list_tasks(state: Optional[str] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Cluster-wide task table: latest known state per task from the GCS
    task-event table (reference: ``list_tasks``, `api.py:1009`), including
    tasks executed on OTHER nodes."""
    rows = _task_table_call("list_task_events", state=state, limit=limit)
    return list(rows or [])


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    """Object metadata known to the connected raylet.  Routed through the
    raylet-thread ``state_snapshot`` (never reads ``_objects`` off-thread)
    with ``limit`` applied at the source, before materializing."""
    snap = _snapshot(objects_limit=max(1, limit))
    return list(snap.get("objects", {}).get("items") or [])


def summarize_tasks() -> Dict[str, int]:
    """State -> count, cluster-wide (reference: ``summarize_tasks``,
    `api.py:1367`)."""
    summary = _task_table_call("summarize_task_events")
    return dict((summary or {}).get("by_state", {}))


def task_events_summary() -> Dict[str, Any]:
    """Full task-event accounting: state counts, distinct reporting nodes,
    and the cluster-wide export drop counter (ring-buffer backpressure)."""
    return dict(_task_table_call("summarize_task_events") or {})


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects(limit=100000)
    by_status: Dict[str, int] = {}
    for o in objs:
        by_status[o["status"]] = by_status.get(o["status"], 0) + 1
    return {"total": len(objs), "by_status": by_status,
            "bytes_known": sum(o.get("size", 0) for o in objs)}


# --------------------------------------------------------------- timeline


def build_timeline(events: List[dict], spans: Optional[List[dict]] = None,
                   now: Optional[float] = None) -> List[dict]:
    """chrome://tracing trace from raw task events (and, when tracing is
    on, driver-side submit spans).

    Per task attempt, TWO sub-slices make queue wait visible next to run
    time: ``queue_wait`` (QUEUED/PENDING_ARGS -> dispatch) and ``run``
    (dispatch -> terminal).  Still-in-flight tasks get an OPEN-ENDED slice
    ending at ``now`` instead of being silently dropped, and tasks that
    fail before dispatch close their queue slice at the failure — nothing
    leaks (reference: ``ray.timeline``, `python/ray/_private/state.py:416`).
    Submit spans become flow arrows (``s``/``f``) from the submitting
    process to the first run slice of the task.
    """
    now = time.time() if now is None else now
    per_task: Dict[str, List[dict]] = {}
    for ev in sorted(events, key=lambda e: e.get("time", 0.0)):
        per_task.setdefault(ev["task_id"], []).append(ev)

    trace: List[dict] = []
    first_run: Dict[str, dict] = {}  # task_id -> first run slice (flow tgt)

    def emit(name, phase, t0, t1, pid, tid_hex, **args):
        sl = {
            "cat": "task", "name": name, "ph": "X",
            "ts": t0 * 1e6, "dur": max(0.0, (t1 - t0)) * 1e6,
            "pid": pid, "tid": pid,
            "args": {"phase": phase, "task_id": tid_hex, **args},
        }
        trace.append(sl)
        return sl

    for tid, evs in per_task.items():
        name = next((e.get("name") for e in evs if e.get("name")), tid[:8])
        queued_t: Optional[float] = None
        run_t: Optional[float] = None
        pid = 0
        node = evs[-1].get("node_id", "")
        # task events <-> traces: a sampled request's timeline slices
        # carry its trace id, so a slow slice jumps to its waterfall
        trace_id = next((e["trace_id"] for e in evs
                         if e.get("trace_id")), None)
        targs = {"trace_id": trace_id} if trace_id else {}
        for ev in evs:
            st = ev.get("state")
            t = ev.get("time", 0.0)
            if st in ("PENDING_ARGS", "QUEUED", "PENDING"):
                if queued_t is None:
                    queued_t = t
            elif st in ("RUNNING", "DISPATCHED"):
                if run_t is None:
                    run_t = t
                    pid = ev.get("pid") or 0
                    if queued_t is not None:
                        emit(name, "queue_wait", queued_t, t, pid, tid,
                             node_id=ev.get("node_id", node), **targs)
                        queued_t = None
            elif st in ("FINISHED", "FAILED", "OOM_KILLED"):
                start = run_t if run_t is not None else t
                sl = emit(name, "run", start, t, pid, tid, state=st,
                          node_id=ev.get("node_id", node), **targs,
                          **({"error": ev["error"]} if ev.get("error")
                             else {}))
                first_run.setdefault(tid, sl)
                run_t = queued_t = None
            elif st in ("RETRYING", "REQUEUED", "SPILLED", "FORWARDED",
                        "RECONSTRUCTING"):
                # attempt boundary: close whatever phase was open here
                if run_t is not None:
                    sl = emit(name, "run", run_t, t, pid, tid, state=st,
                              node_id=ev.get("node_id", node), **targs)
                    first_run.setdefault(tid, sl)
                elif queued_t is not None:
                    emit(name, "queue_wait", queued_t, t, pid, tid, state=st,
                         node_id=ev.get("node_id", node), **targs)
                run_t = queued_t = None
        # in-flight work: open-ended slices up to `now` (never dropped)
        if run_t is not None:
            sl = emit(name, "run", run_t, now, pid, tid, state="RUNNING",
                      in_flight=True, node_id=node, **targs)
            first_run.setdefault(tid, sl)
        elif queued_t is not None:
            emit(name, "queue_wait", queued_t, now, pid, tid,
                 in_flight=True, node_id=node, **targs)

    # flow arrows from submit spans (tracing on): submitting process ->
    # the task's first run slice
    for sp in spans or []:
        tid = (sp.get("attributes") or {}).get("task_id")
        if not tid or not str(sp.get("name", "")).startswith("task.submit"):
            continue
        t0 = sp.get("start_us", 0) / 1e6
        t1 = t0 + sp.get("duration_us", 0) / 1e6
        spid = sp.get("pid", 0)
        trace.append({"cat": "submit", "name": sp["name"], "ph": "X",
                      "ts": t0 * 1e6,
                      "dur": sp.get("duration_us", 0), "pid": spid,
                      "tid": spid, "args": {"task_id": tid}})
        target = first_run.get(tid)
        if target is None:
            continue
        trace.append({"cat": "flow", "name": "submit", "ph": "s",
                      "id": tid, "ts": t1 * 1e6, "pid": spid, "tid": spid})
        trace.append({"cat": "flow", "name": "submit", "ph": "f",
                      "bp": "e", "id": tid, "ts": target["ts"],
                      "pid": target["pid"], "tid": target["tid"]})
    return trace


def raw_task_events(limit: int = 100000) -> List[dict]:
    """The cluster-wide raw event log (every recorded transition)."""
    return list(_task_table_call("task_events_raw", limit=limit) or [])
