"""State API — queryable cluster state.

Reference analogue: `python/ray/util/state/api.py` (``list_actors`` `:782`,
``list_nodes`` `:874`, ``list_tasks`` `:1009`, ``list_objects`` `:1054`,
``summarize_tasks`` `:1367`) over the dashboard's StateAggregator.  Here the
sources are the GCS tables (nodes/actors — cluster-wide) and the connected
raylet's snapshot (tasks/objects — node-local views; cluster-wide task
aggregation lands with GCS task-event export).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional

from ray_tpu.core.worker import global_worker


def _snapshot() -> dict:
    w = global_worker()
    if w.mode == "driver":
        return w.raylet.call(w.raylet.state_snapshot).result()
    if w.mode == "local":
        return {"node_id": "local", "tasks": [], "actors": [],
                "objects": {"num": 0}, "events": [],
                "resources_total": {}, "resources_available": {}}
    return w._request("state_snapshot")


def list_nodes() -> List[Dict[str, Any]]:
    """Cluster membership with resources (GCS node table)."""
    w = global_worker()
    return [
        {
            "node_id": n["node_id"],
            "state": "ALIVE" if n.get("alive", True) else "DEAD",
            "address": n.get("address"),
            "hostname": n.get("hostname", ""),
            "resources_total": n.get("resources_total", {}),
            "resources_available": n.get("resources_available", {}),
        }
        for n in w.gcs_nodes()
    ]


def list_actors(state: Optional[str] = None) -> List[Dict[str, Any]]:
    """Cluster-wide actor table (GCS) merged with the local raylet's
    richer per-actor detail when available."""
    w = global_worker()
    local = {a["actor_id"]: a for a in _snapshot().get("actors", [])}
    if w.mode == "driver":
        gcs_actors = w.raylet.gcs.list_actors()
    elif w.mode == "client":
        gcs_actors = w.gcs.list_actors()
    elif w.mode == "worker":
        gcs_actors = w._request("gcs_list_actors")
    else:
        gcs_actors = []
    out = {}
    for a in gcs_actors:
        out[a["actor_id"]] = {
            "actor_id": a["actor_id"],
            "state": a.get("state", "?").upper(),
            "name": a.get("name"),
            "owner_node": a.get("owner_node"),
        }
    for aid, a in local.items():
        entry = out.setdefault(aid, {"actor_id": aid})
        entry.update({
            "state": a["state"].upper(),
            "name": a.get("name"),
            "pid": a.get("pid"),
        })
    results = list(out.values())
    if state is not None:
        results = [a for a in results if a.get("state") == state.upper()]
    return results


def list_tasks(state: Optional[str] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Task table from the connected raylet's event log (latest state per
    task)."""
    tasks = list(_snapshot().get("tasks", []))
    if state is not None:
        tasks = [t for t in tasks if t["state"] == state.upper()]
    return tasks[:limit]


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    """Object metadata known to the connected raylet."""
    w = global_worker()
    if w.mode != "driver":
        snap = _snapshot()
        return [{"count": snap.get("objects", {}).get("num", 0)}]

    def collect():
        return [
            {
                "object_id": oid.hex(),
                "status": st.status,
                "size": st.size,
                "locations": list(st.locations),
            }
            for oid, st in list(w.raylet._objects.items())[:limit]
        ]

    return w.raylet.call(collect).result()


def summarize_tasks() -> Dict[str, int]:
    """State -> count (reference: ``summarize_tasks``, `api.py:1367`)."""
    return dict(Counter(t["state"] for t in _snapshot().get("tasks", [])))


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects(limit=100000)
    if objs and "status" in objs[0]:
        by_status = Counter(o["status"] for o in objs)
        return {"total": len(objs), "by_status": dict(by_status),
                "bytes_known": sum(o.get("size", 0) for o in objs)}
    return {"total": objs[0]["count"] if objs else 0}
